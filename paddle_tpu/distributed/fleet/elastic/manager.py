"""ElasticManager: heartbeat watch + restart decisions.

Reference behavior (upstream python/paddle/distributed/fleet/elastic/
manager.py): workers register in etcd under a job prefix with TTL leases;
the manager's watch loop classifies the job as HOLD (membership incomplete),
RESTART (fault detected, respawn), COMPLETED, or EXIT (restarts exhausted).
This module keeps those states and the watch-loop shape, over our TCPStore.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .... import observability as _obs
from ....resilience import jitter_sleep as _jitter_sleep

__all__ = [
    "ElasticLevel", "ElasticStatus", "ElasticManager", "enable_elastic",
    "start_worker_heartbeat", "ELASTIC_ENV_MASTER", "ELASTIC_ENV_RESTARTS",
]

_log = logging.getLogger(__name__)

ELASTIC_ENV_MASTER = "PADDLE_ELASTIC_MASTER"      # host:port of the beat store
ELASTIC_ENV_RESTARTS = "PADDLE_RESTART_COUNT"     # bumped on every respawn


class ElasticLevel:
    NONE = 0
    FAULT_TOLERANCE = 1   # restart on fault, same world size
    ELASTIC = 2           # resize on membership change


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


def enable_elastic(args=None, etcd_client=None) -> bool:
    """Parity helper: elastic is on when an elastic level > 0 is requested
    (upstream also requires an etcd endpoint; we self-host the store)."""
    level = getattr(args, "elastic_level", None)
    if level is None:
        level = int(os.environ.get("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", 0))
    return int(level) > 0


def start_worker_heartbeat(rank: Optional[int] = None,
                           interval: float = 2.0) -> Optional[threading.Thread]:
    """Worker side: lease ``elastic/beat/{rank}`` in the manager's store from
    a daemon thread. Called automatically by ``init_parallel_env`` when the
    launcher exported :data:`ELASTIC_ENV_MASTER`; safe no-op otherwise."""
    master = os.environ.get(ELASTIC_ENV_MASTER)
    if not master:
        return None
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    host, port = master.rsplit(":", 1)

    from ...store import TCPStore
    store = TCPStore(host, int(port))

    def beat() -> None:
        while True:
            try:
                store.set(f"elastic/beat/{rank}", str(time.time()))
            except Exception:
                return  # manager gone: job is shutting down
            # jittered (±25%): a pod of workers respawned together must
            # not lease in phase against the manager's store forever
            _jitter_sleep(interval)

    t = threading.Thread(target=beat, daemon=True,
                         name=f"elastic-heartbeat-{rank}")
    t.start()
    return t


class ElasticManager:
    """Launcher-side watch loop.

    ``procs`` liveness is the primary fault signal (a dead worker process is
    definitive); heartbeat staleness catches hangs — a worker that is alive
    but has stopped making progress past ``beat_timeout``.
    """

    def __init__(self, world_size: int,
                 elastic_level: int = ElasticLevel.FAULT_TOLERANCE,
                 beat_timeout: float = 30.0, max_restarts: int = 3,
                 store=None, rank_offset: int = 0,
                 single_node: bool = True):
        self.world_size = world_size
        # level-2 RESIZE only acts when this manager supervises the whole
        # job (single node): node-local managers resizing independently
        # would desync PADDLE_TRAINERS_NUM across nodes — multi-node jobs
        # keep level-1 same-size restart semantics
        self.single_node = bool(single_node)
        # first GLOBAL rank of the locally-supervised procs (multi-node:
        # node_rank * nproc_per_node); beat keys are global-rank keyed
        self.rank_offset = rank_offset
        self.elastic_level = elastic_level
        self.beat_timeout = beat_timeout
        self.max_restarts = max_restarts
        self.restarts = 0
        if store is None:
            from ...store import TCPStore
            store = TCPStore(is_master=True, world_size=world_size)
        self.store = store
        self._started = time.time()
        self._beat_fail_throttle = _obs.LogThrottle()

    @property
    def endpoint(self) -> str:
        return f"127.0.0.1:{self.store.port}"

    def worker_env(self) -> Dict[str, str]:
        """Extra env for spawned workers."""
        return {
            ELASTIC_ENV_MASTER: self.endpoint,
            ELASTIC_ENV_RESTARTS: str(self.restarts),
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL": str(self.elastic_level),
        }

    # --- fault classification -------------------------------------------------
    def _beat_age(self, rank: int) -> Optional[float]:
        try:
            if not self.store.check(f"elastic/beat/{rank}"):
                return None  # never registered: not hang-monitored
            raw = self.store.get(f"elastic/beat/{rank}", timeout=1.0)
        except Exception as e:
            # unreadable lease -> hang detection is OFF for this worker;
            # counted so a flaky local store is visible, not silent. The
            # log is rate-limited (1/10s): a dead store fails every rank
            # every watch tick and the counter already carries magnitude
            _obs.inc("elastic.store_read_failures_total")
            if self._beat_fail_throttle.ready():
                _log.warning("elastic: beat read for rank %s failed "
                             "(%s: %s)", rank, type(e).__name__, e)
            return None
        try:
            age = time.time() - float(raw.decode())
        except (ValueError, AttributeError):
            return None  # malformed lease payload: not hang-monitored
        _obs.set_gauge("elastic.worker_beat_age_seconds", age, rank=rank)
        return age

    def classify(self, procs: List) -> str:
        """One watch tick over child processes + leases. Also records the
        MEMBERSHIP LOSS of the tick (``_last_dead``): fault-exited plus
        hung workers — the resize input for ``ElasticLevel.ELASTIC``."""
        codes = [p.poll() for p in procs]
        self._last_dead = sum(1 for c in codes if c is not None and c != 0)
        if all(c == 0 for c in codes):
            return ElasticStatus.COMPLETED
        if any(c is not None and c != 0 for c in codes):
            return (ElasticStatus.RESTART
                    if self.restarts < self.max_restarts
                    else ElasticStatus.ERROR)
        # remaining procs are running or exited clean: check RUNNING workers
        # for hangs via lease freshness (a worker that exited 0 naturally
        # stops beating — that is not a hang; and a script that never
        # registered a beat simply isn't hang-monitored)
        hung = 0
        for i, code in enumerate(codes):
            if code == 0:
                continue
            age = self._beat_age(self.rank_offset + i)
            if age is not None and age > self.beat_timeout:
                hung += 1
        if hung:
            self._last_dead += hung
            return (ElasticStatus.RESTART
                    if self.restarts < self.max_restarts
                    else ElasticStatus.ERROR)
        return ElasticStatus.HOLD

    # --- the loop -------------------------------------------------------------
    def watch(self, procs: List, respawn: Callable[[int], List],
              poll_interval: float = 1.0) -> int:
        """Supervise ``procs`` until completion or restart exhaustion.

        ``respawn(restart_count)`` must kill-and-recreate the worker list
        (the launcher owns process creation). Returns the exit code."""
        while True:
            status = self.classify(procs)
            if status == ElasticStatus.COMPLETED:
                return 0
            if status == ElasticStatus.ERROR:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                return 1
            if status == ElasticStatus.RESTART:
                self.restarts += 1
                _obs.inc("elastic.restarts_total")
                if (self.elastic_level >= ElasticLevel.ELASTIC
                        and self.single_node):
                    # level 2 (resize): the lost members LEAVE the job —
                    # recompute the world to the surviving count and restart
                    # on the smaller topology (ranks remapped 0..new-1 by
                    # the launcher's respawn; workers resume from
                    # checkpoint). Upstream: the etcd membership watch in
                    # fleet/elastic/manager.py shrinking np on node loss.
                    dead = max(1, getattr(self, "_last_dead", 1))
                    new_world = max(1, self.world_size - dead)
                    if new_world != self.world_size:
                        self.world_size = new_world
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except Exception:
                        p.kill()
                self._clear_beats()
                procs = respawn(self.restarts)
                continue
            # jittered so simultaneously-restarted node managers spread
            # their store-health polling instead of stampeding rank 0
            _jitter_sleep(poll_interval)

    def _clear_beats(self) -> None:
        """Delete (not re-seed) leases: a seeded key would falsely register a
        worker that never heartbeats, turning every restart into a hang.
        Clears THIS manager's global-rank window (multi-node: a node
        supervises ranks [rank_offset, rank_offset + world_size))."""
        for rank in range(self.rank_offset,
                          self.rank_offset + self.world_size):
            try:
                self.store.delete_key(f"elastic/beat/{rank}")
            except Exception:
                pass  # key absent / store blip: a stale lease only delays
                #       hang detection by one beat interval


class MultiNodeElasticAgent:
    """Per-NODE launcher agent: level-2 resize beyond one node (round 5;
    upstream parity: the etcd-backed membership watch in
    fleet/elastic/manager.py, where every node's launcher leases itself
    into etcd and np shrinks when a node lease expires).

    Coordination rides one SHARED job store (the analogue of upstream's
    external etcd — host it outside the trainer nodes so any node may
    die):

    * every agent leases ``elastic/node/{node_rank}`` each tick;
    * the SUPERVISOR is the lowest-ranked live node of the current
      topology — election is implicit in the lease set, so it survives
      the supervisor's own death (the next-lowest node takes over on the
      next tick);
    * only the supervisor writes ``elastic/topology`` records
      ``{epoch, nodes, restarts}``: node-loss (stale lease) at level 2
      shrinks ``nodes``; a worker fault flagged by any agent
      (``elastic/fault/{node}``) keeps ``nodes`` and bumps the epoch so
      every pod restarts together (the collective must re-form);
    * every agent ADOPTS a newer epoch: kill local workers, respawn via
      the launcher-provided callable with its new node index and world.

    Completion: an agent whose pod exits clean sets ``elastic/done`` and
    waits for the other live nodes (so a late resize still finds a
    supervisor).
    """

    def __init__(self, node_rank: int, nnodes: int, nproc_per_node: int,
                 store, elastic_level: int = ElasticLevel.ELASTIC,
                 beat_timeout: float = 30.0, node_timeout: float = 10.0,
                 max_restarts: int = 3, node_grace: float = 120.0,
                 master_endpoint: Optional[str] = None,
                 store_lost_deadline: float = 60.0):
        # the address WORKERS dial for heartbeats — must be the shared
        # store's routable endpoint, not loopback, on real multi-host jobs
        self.master_endpoint = master_endpoint
        self.node_rank = int(node_rank)
        self.nproc = int(nproc_per_node)
        self.elastic_level = int(elastic_level)
        self.node_timeout = float(node_timeout)
        self.max_restarts = int(max_restarts)
        # rolling starts: a node that has NEVER leased is presumed coming
        # up (not lost) until the grace window ends — resizing it away at
        # t=0 would shrink a job that was merely starting unevenly
        self.node_grace = float(node_grace)
        self._started = time.time()
        self.store = store
        # store health (ADVICE r5): a read failure must never read as "node
        # is healthy" forever — consecutive failures are counted and, past
        # the deadline, the store is declared LOST and watch() exits loudly
        self.store_lost_deadline = float(store_lost_deadline)
        self.store_lost = False
        self._store_fail_first: Optional[float] = None
        self._store_fail_count = 0
        self._read_fail_throttle = _obs.LogThrottle()
        self._write_fail_throttle = _obs.LogThrottle()
        # per-KEY read-failure windows (keyed by node rank for leases,
        # by key name otherwise). A lease key failing past the deadline
        # reads as a LOST NODE (evictable); a coordination key
        # (topology/fault/done) failing past it means the agent can no
        # longer coordinate at all and escalates to store-LOST — even
        # while other keys read fine and keep resetting the global
        # window.
        self._key_fail_first: Dict[Any, float] = {}
        self.epoch = 0
        self.nodes = list(range(int(nnodes)))  # current topology
        self._local = ElasticManager(
            world_size=self.nproc, elastic_level=elastic_level,
            beat_timeout=beat_timeout, max_restarts=max_restarts,
            store=store, rank_offset=self.node_rank * self.nproc)

    # -- store records -------------------------------------------------------
    def _beat(self) -> None:
        self.store.set(f"elastic/node/{self.node_rank}", str(time.time()))

    def _store_read_failed(self, what, exc: BaseException) -> None:
        """Track CONSECUTIVE store read failures (ADVICE r5: these used to
        map silently to age 0.0 = "healthy node", so a dead store meant
        dead nodes were live forever and the job hung signal-free). Every
        failure is counted + logged; past ``store_lost_deadline`` seconds
        of unbroken failures the store is declared lost, which watch()
        turns into a loud exit instead of an invisible hang."""
        now = time.monotonic()
        if self._store_fail_first is None:
            self._store_fail_first = now
        self._store_fail_count += 1
        self._key_fail_first.setdefault(what, now)
        _obs.inc("elastic.store_read_failures_total")
        # throttled on a MONOTONIC clock that window resets never rewind:
        # one flaky node among healthy ones resets the consecutive-failure
        # window every tick, and that must not grant a fresh log line each
        # time — at most one per 10s, period
        if self._read_fail_throttle.ready():
            _log.warning(
                "elastic: job-store read of %s failed (%s: %s; "
                "%d consecutive failure(s) over %.1fs)", what,
                type(exc).__name__, exc, self._store_fail_count,
                now - self._store_fail_first)
        # escalate on EITHER signal: the whole store failing unbroken
        # past the deadline, or a COORDINATION key (non-lease: topology,
        # fault/N, done/N) unreadable past it — healthy lease reads reset
        # the global window every tick, so without the per-key check a
        # permanently unreadable fault flag would hang the job silently.
        # (An unreadable LEASE key instead evicts just that node, via
        # _node_failed_past_deadline in _node_age.)
        key_dead = (not isinstance(what, int)
                    and now - self._key_fail_first[what]
                    > self.store_lost_deadline)
        if key_dead or                 now - self._store_fail_first > self.store_lost_deadline:
            if not self.store_lost:
                _log.error(
                    "elastic: job-store read of %s failing for %.0fs "
                    "(deadline %.0fs) — declaring the store LOST", what,
                    now - self._key_fail_first[what], self.store_lost_deadline)
            self.store_lost = True

    def _store_read_ok(self, what: Optional[Any] = None) -> None:
        self._store_fail_first = None
        self._store_fail_count = 0
        if what is not None:
            self._key_fail_first.pop(what, None)

    def _store_write_failed(self, what: str, exc: BaseException) -> None:
        """Count every store write failure; log at most one line per 10s
        (a write-dead store fails every tick — the counter carries the
        magnitude, same policy as the read path)."""
        _obs.inc("elastic.store_write_failures_total")
        if self._write_fail_throttle.ready():
            _log.warning("elastic: job-store write of %s failed (%s: %s)",
                         what, type(exc).__name__, exc)

    def _node_failed_past_deadline(self, node: int) -> bool:
        """True once THIS node's reads have failed unbroken past the
        deadline while the store itself may be healthy (other nodes
        reading fine keep resetting the global window): its lease is
        effectively unreadable, and an unreadable lease is a lost lease —
        eternal age-0 "freshness" would make the node unevictable."""
        first = self._key_fail_first.get(node)
        return (first is not None
                and time.monotonic() - first > self.store_lost_deadline)

    def _node_age(self, node: int) -> Optional[float]:
        """None = never leased; a TRANSIENT store error still reads as age
        0 (fresh) — one 1-second read hiccup must not count a healthy node
        as lost and permanently shrink the job — but the failure is now
        counted, logged, and escalated via ``_store_read_failed`` (whole
        store) / ``_node_failed_past_deadline`` (single unreadable lease).
        """
        try:
            if not self.store.check(f"elastic/node/{node}"):
                self._store_read_ok(node)
                return None
        except Exception as e:
            self._store_read_failed(node, e)
            return None if self._node_failed_past_deadline(node) else 0.0
        try:
            raw = self.store.get(f"elastic/node/{node}", timeout=1.0)
            age = time.time() - float(raw.decode())
        except Exception as e:
            self._store_read_failed(node, e)
            return None if self._node_failed_past_deadline(node) else 0.0
        self._store_read_ok(node)
        _obs.set_gauge("elastic.node_age_seconds", age, node=node)
        return age

    def _live_nodes(self) -> List[int]:
        in_grace = time.time() - self._started < self.node_grace
        live = []
        for n in self.nodes:
            age = self._node_age(n)
            if age is None:
                if in_grace:
                    live.append(n)  # not yet registered: presumed starting
            elif age <= self.node_timeout:
                live.append(n)
        return live

    def _read_topology(self) -> Optional[Dict]:
        """Every agent store read routes through the health seam: a store
        that serves node leases but consistently fails other keys must
        still count failures and eventually trip the LOST escalation —
        otherwise a crashed pod whose fault flag is unreadable hangs the
        job with zero signal (the original ADVICE r5 class)."""
        try:
            if not self.store.check("elastic/topology"):
                self._store_read_ok("topology")
                return None
            topo = json.loads(self.store.get("elastic/topology",
                                             timeout=1.0).decode())
        except Exception as e:
            self._store_read_failed("topology", e)
            return None
        self._store_read_ok("topology")
        return topo

    def _write_exit(self) -> None:
        """Publish a terminal record: restart budget exhausted — every
        adopter terminates its pod and exits 1 (instead of the whole job
        hanging with dead workers)."""
        self.store.set("elastic/topology", json.dumps(
            {"epoch": self.epoch + 1, "nodes": sorted(self.nodes),
             "restarts": self._local.restarts, "exit": True}).encode())

    def _write_topology(self, nodes: List[int], restarts: int) -> None:
        """Publish the next-epoch topology WITHOUT adopting it: the
        supervisor applies its own record through the same adoption path
        as every other node on its next tick (pre-bumping self.epoch here
        would make the supervisor skip its own resize — found by the
        kill-a-node test running the dead topology to completion)."""
        self.store.set("elastic/topology", json.dumps(
            {"epoch": self.epoch + 1, "nodes": sorted(nodes),
             "restarts": restarts}).encode())

    def _my_index(self) -> int:
        return self.nodes.index(self.node_rank)

    def world_size(self) -> int:
        return len(self.nodes) * self.nproc

    def worker_env(self) -> Dict[str, str]:
        env = self._local.worker_env()
        if self.master_endpoint:
            env[ELASTIC_ENV_MASTER] = self.master_endpoint
        # the reload-your-checkpoint signal follows the EPOCH (resizes
        # bump it too), not the fault-restart budget counter
        env[ELASTIC_ENV_RESTARTS] = str(self.epoch)
        return env

    # -- the loop ------------------------------------------------------------
    def watch(self, procs: List, respawn: Callable[..., List],
              poll_interval: float = 0.5) -> int:
        """Supervise this node's pod; coordinate restarts/resizes through
        the shared store. ``respawn(epoch, node_index, topology_nodes)``
        recreates the local worker list for the CURRENT topology
        (``topology_nodes`` carries the surviving ORIGINAL node ranks so
        the launcher can map operator-provided per-node endpoints)."""
        done = False
        warned_lost: List[int] = []

        def _safe_set(key, val):
            # the shared store may blip (or its host may be the one that
            # died) — supervision must keep looping, not unwind and
            # orphan the running workers; the failure is still counted
            try:
                self.store.set(key, val)
                return True
            except Exception as e:
                self._store_write_failed(key, e)
                return False

        while True:
            if self.store_lost:
                # reads have failed past the deadline: the agent can no
                # longer tell live nodes from dead ones, adopt topologies,
                # or be seen by the supervisor — exit loudly instead of
                # supervising blind (ADVICE r5)
                _log.error("elastic: job store lost; terminating local "
                           "workers and exiting")
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except Exception:
                        p.kill()  # SIGTERM ignored (hung collective): force
                return 1
            try:
                self._beat()
            except Exception as e:
                self._store_write_failed("node beat", e)
            # 1. adopt a newer topology (written by the supervisor)
            topo = self._read_topology()
            if topo and topo["epoch"] > self.epoch:
                self.epoch = topo["epoch"]
                self.nodes = list(topo["nodes"])
                self._local.restarts = int(topo["restarts"])
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except Exception:
                        p.kill()
                if topo.get("exit"):
                    return 1  # restart budget exhausted: terminal record
                if self.node_rank not in self.nodes:
                    return 0  # evicted (we were presumed dead): stand down
                self._local.rank_offset = self._my_index() * self.nproc
                self._local.world_size = self.nproc
                self._local._clear_beats()
                try:
                    self.store.delete_key(f"elastic/fault/{self.node_rank}")
                except Exception as e:
                    self._store_write_failed("fault-flag delete", e)
                    # a lingering fault flag of an OLD epoch is ignored by
                    # the epoch-scoped fault check; safe to continue
                done = False
                procs = respawn(self.epoch, self._my_index(),
                                list(self.nodes))
                continue

            # 2. local pod state
            status = self._local.classify(procs)
            if status == ElasticStatus.COMPLETED and not done:
                # EPOCH-scoped: a done flag from a pre-restart epoch must
                # not satisfy this epoch's completion check
                done = _safe_set(f"elastic/done/{self.node_rank}",
                                 str(self.epoch))
            if done:
                # hold until every live node is done (a supervisor must
                # remain for stragglers' resizes), then stand down
                live = self._live_nodes()
                if all(self._done_epoch(n) >= self.epoch for n in live):
                    return 0
            elif status in (ElasticStatus.RESTART, ElasticStatus.ERROR):
                # flag the fault (epoch-tagged); the SUPERVISOR decides
                # between a restart and a terminal exit record — a local
                # return here would leave the other nodes waiting forever
                _safe_set(f"elastic/fault/{self.node_rank}",
                          str(self.epoch))

            # 3. supervisor duties
            live = self._live_nodes()
            if live and self.node_rank == min(live):
                lost = [n for n in self.nodes if n not in live]
                # a fault flag only counts for the CURRENT epoch — the
                # flag of a fault the last restart already resolved must
                # not burn a second restart
                faults = [n for n in self.nodes
                          if self._fault_epoch(n) >= self.epoch]
                if lost and self.elastic_level >= ElasticLevel.ELASTIC:
                    # RESIZE: drop the dead nodes, everyone restarts on
                    # the smaller topology. Does NOT consume the
                    # fault-restart budget — checkpoint reload is keyed
                    # on the epoch, which bumps anyway.
                    try:
                        self._write_topology(live, self._local.restarts)
                    except Exception as e:
                        self._store_write_failed("resize topology", e)
                        # store blip: the resize retries next tick
                elif lost:
                    if lost != warned_lost:  # level 1: hold for rejoin
                        warned_lost = list(lost)
                        print(f"elastic: node(s) {lost} lost; level-1 "
                              "holds for rejoin (level 2 would resize)",
                              flush=True)
                elif faults:
                    if self._local.restarts + 1 > self.max_restarts:
                        try:
                            self._write_exit()
                        except Exception as e:
                            self._store_write_failed("exit record", e)
                            # store blip: the exit record retries next tick
                    else:
                        # same-size restart across all pods
                        try:
                            self._write_topology(self.nodes,
                                                 self._local.restarts + 1)
                        except Exception as e:
                            self._store_write_failed("restart topology", e)
                            # store blip: retried next tick
            # jittered: after an epoch adoption every agent's watch tick
            # fires at the same instant; desynchronize the shared-store
            # lease/topology reads across nodes
            _jitter_sleep(poll_interval)

    def _done_epoch(self, node: int) -> int:
        try:
            if not self.store.check(f"elastic/done/{node}"):
                self._store_read_ok(f"done/{node}")
                return -1
            epoch = int(self.store.get(f"elastic/done/{node}",
                                       timeout=1.0).decode())
        except Exception as e:
            self._store_read_failed(f"done/{node}", e)
            return -1
        self._store_read_ok(f"done/{node}")
        return epoch

    def _fault_epoch(self, node: int) -> int:
        try:
            if not self.store.check(f"elastic/fault/{node}"):
                self._store_read_ok(f"fault/{node}")
                return -1
            epoch = int(self.store.get(f"elastic/fault/{node}",
                                       timeout=1.0).decode())
        except Exception as e:
            self._store_read_failed(f"fault/{node}", e)
            return -1
        self._store_read_ok(f"fault/{node}")
        return epoch
