"""ElasticManager: heartbeat watch + restart decisions.

Reference behavior (upstream python/paddle/distributed/fleet/elastic/
manager.py): workers register in etcd under a job prefix with TTL leases;
the manager's watch loop classifies the job as HOLD (membership incomplete),
RESTART (fault detected, respawn), COMPLETED, or EXIT (restarts exhausted).
This module keeps those states and the watch-loop shape, over our TCPStore.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "ElasticLevel", "ElasticStatus", "ElasticManager", "enable_elastic",
    "start_worker_heartbeat", "ELASTIC_ENV_MASTER", "ELASTIC_ENV_RESTARTS",
]

ELASTIC_ENV_MASTER = "PADDLE_ELASTIC_MASTER"      # host:port of the beat store
ELASTIC_ENV_RESTARTS = "PADDLE_RESTART_COUNT"     # bumped on every respawn


class ElasticLevel:
    NONE = 0
    FAULT_TOLERANCE = 1   # restart on fault, same world size
    ELASTIC = 2           # resize on membership change


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


def enable_elastic(args=None, etcd_client=None) -> bool:
    """Parity helper: elastic is on when an elastic level > 0 is requested
    (upstream also requires an etcd endpoint; we self-host the store)."""
    level = getattr(args, "elastic_level", None)
    if level is None:
        level = int(os.environ.get("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", 0))
    return int(level) > 0


def start_worker_heartbeat(rank: Optional[int] = None,
                           interval: float = 2.0) -> Optional[threading.Thread]:
    """Worker side: lease ``elastic/beat/{rank}`` in the manager's store from
    a daemon thread. Called automatically by ``init_parallel_env`` when the
    launcher exported :data:`ELASTIC_ENV_MASTER`; safe no-op otherwise."""
    master = os.environ.get(ELASTIC_ENV_MASTER)
    if not master:
        return None
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    host, port = master.rsplit(":", 1)

    from ...store import TCPStore
    store = TCPStore(host, int(port))

    def beat() -> None:
        while True:
            try:
                store.set(f"elastic/beat/{rank}", str(time.time()))
            except Exception:
                return  # manager gone: job is shutting down
            time.sleep(interval)

    t = threading.Thread(target=beat, daemon=True,
                         name=f"elastic-heartbeat-{rank}")
    t.start()
    return t


class ElasticManager:
    """Launcher-side watch loop.

    ``procs`` liveness is the primary fault signal (a dead worker process is
    definitive); heartbeat staleness catches hangs — a worker that is alive
    but has stopped making progress past ``beat_timeout``.
    """

    def __init__(self, world_size: int,
                 elastic_level: int = ElasticLevel.FAULT_TOLERANCE,
                 beat_timeout: float = 30.0, max_restarts: int = 3,
                 store=None, rank_offset: int = 0,
                 single_node: bool = True):
        self.world_size = world_size
        # level-2 RESIZE only acts when this manager supervises the whole
        # job (single node): node-local managers resizing independently
        # would desync PADDLE_TRAINERS_NUM across nodes — multi-node jobs
        # keep level-1 same-size restart semantics
        self.single_node = bool(single_node)
        # first GLOBAL rank of the locally-supervised procs (multi-node:
        # node_rank * nproc_per_node); beat keys are global-rank keyed
        self.rank_offset = rank_offset
        self.elastic_level = elastic_level
        self.beat_timeout = beat_timeout
        self.max_restarts = max_restarts
        self.restarts = 0
        if store is None:
            from ...store import TCPStore
            store = TCPStore(is_master=True, world_size=world_size)
        self.store = store
        self._started = time.time()

    @property
    def endpoint(self) -> str:
        return f"127.0.0.1:{self.store.port}"

    def worker_env(self) -> Dict[str, str]:
        """Extra env for spawned workers."""
        return {
            ELASTIC_ENV_MASTER: self.endpoint,
            ELASTIC_ENV_RESTARTS: str(self.restarts),
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL": str(self.elastic_level),
        }

    # --- fault classification -------------------------------------------------
    def _beat_age(self, rank: int) -> Optional[float]:
        try:
            if not self.store.check(f"elastic/beat/{rank}"):
                return None  # never registered: not hang-monitored
            raw = self.store.get(f"elastic/beat/{rank}", timeout=1.0)
        except Exception:
            return None
        try:
            return time.time() - float(raw.decode())
        except (ValueError, AttributeError):
            return None

    def classify(self, procs: List) -> str:
        """One watch tick over child processes + leases. Also records the
        MEMBERSHIP LOSS of the tick (``_last_dead``): fault-exited plus
        hung workers — the resize input for ``ElasticLevel.ELASTIC``."""
        codes = [p.poll() for p in procs]
        self._last_dead = sum(1 for c in codes if c is not None and c != 0)
        if all(c == 0 for c in codes):
            return ElasticStatus.COMPLETED
        if any(c is not None and c != 0 for c in codes):
            return (ElasticStatus.RESTART
                    if self.restarts < self.max_restarts
                    else ElasticStatus.ERROR)
        # remaining procs are running or exited clean: check RUNNING workers
        # for hangs via lease freshness (a worker that exited 0 naturally
        # stops beating — that is not a hang; and a script that never
        # registered a beat simply isn't hang-monitored)
        hung = 0
        for i, code in enumerate(codes):
            if code == 0:
                continue
            age = self._beat_age(self.rank_offset + i)
            if age is not None and age > self.beat_timeout:
                hung += 1
        if hung:
            self._last_dead += hung
            return (ElasticStatus.RESTART
                    if self.restarts < self.max_restarts
                    else ElasticStatus.ERROR)
        return ElasticStatus.HOLD

    # --- the loop -------------------------------------------------------------
    def watch(self, procs: List, respawn: Callable[[int], List],
              poll_interval: float = 1.0) -> int:
        """Supervise ``procs`` until completion or restart exhaustion.

        ``respawn(restart_count)`` must kill-and-recreate the worker list
        (the launcher owns process creation). Returns the exit code."""
        while True:
            status = self.classify(procs)
            if status == ElasticStatus.COMPLETED:
                return 0
            if status == ElasticStatus.ERROR:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                return 1
            if status == ElasticStatus.RESTART:
                self.restarts += 1
                if (self.elastic_level >= ElasticLevel.ELASTIC
                        and self.single_node):
                    # level 2 (resize): the lost members LEAVE the job —
                    # recompute the world to the surviving count and restart
                    # on the smaller topology (ranks remapped 0..new-1 by
                    # the launcher's respawn; workers resume from
                    # checkpoint). Upstream: the etcd membership watch in
                    # fleet/elastic/manager.py shrinking np on node loss.
                    dead = max(1, getattr(self, "_last_dead", 1))
                    new_world = max(1, self.world_size - dead)
                    if new_world != self.world_size:
                        self.world_size = new_world
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except Exception:
                        p.kill()
                self._clear_beats()
                procs = respawn(self.restarts)
                continue
            time.sleep(poll_interval)

    def _clear_beats(self) -> None:
        """Delete (not re-seed) leases: a seeded key would falsely register a
        worker that never heartbeats, turning every restart into a hang."""
        for rank in range(self.world_size):
            try:
                self.store.delete_key(f"elastic/beat/{rank}")
            except Exception:
                pass
