"""Elastic training: fault detection + restart orchestration.

Parity surface: python/paddle/distributed/fleet/elastic/ (upstream
``ElasticManager`` watches etcd-registered workers with TTL leases; on
membership change it recomputes ranks and restarts the job —
``launch --elastic_level 1`` = restart on fault with the same world size
from checkpoint, level 2 = resize).

TPU-native design: no etcd. The coordination plane is the framework's own
``TCPStore`` (paddle_tpu/distributed/store.py — the same rendezvous KV the
collective init uses): workers lease a ``elastic/beat/{rank}`` key via a
daemon heartbeat thread; the launcher-side :class:`ElasticManager` watches
lease freshness plus child-process liveness, and on a fault kills the pod and
respawns it with ``PADDLE_RESTART_COUNT`` bumped so training scripts reload
their latest checkpoint. Slice health on real multi-host TPU rides the same
watch loop (a host that loses its slice stops beating).
"""

from .manager import (ELASTIC_ENV_MASTER, ELASTIC_ENV_RESTARTS,
                      ElasticLevel, ElasticManager, ElasticStatus,
                      MultiNodeElasticAgent, enable_elastic,
                      start_worker_heartbeat)

__all__ = [
    "ElasticLevel", "ElasticManager", "ElasticStatus", "enable_elastic",
    "start_worker_heartbeat", "MultiNodeElasticAgent",
    "ELASTIC_ENV_MASTER", "ELASTIC_ENV_RESTARTS",
]
