"""``paddle.distributed.fleet.utils`` — recompute (activation checkpointing)
and filesystem helpers.

Parity: python/paddle/distributed/fleet/utils/__init__.py (recompute) +
recompute/ package. TPU-native design: reentrant recompute over the eager
tape — forward runs grad-free (no residuals stored), backward re-runs the
function with grad enabled and backprops through the rebuilt subgraph;
closed-over parameters receive their grads from that inner backward. Under
``to_static`` the re-run traces into the compiled backward, which is exactly
XLA rematerialization.
"""

from __future__ import annotations

import os
import shutil

from ....core.random import default_generator
from ....core.tensor import Tensor
from .... import autograd as _autograd
from ....core import tracing as _tracing
from .. import sequence_parallel_utils  # noqa: F401

__all__ = ["recompute", "recompute_sequential", "LocalFS"]


def recompute(function, *args, **kwargs):
    """Activation-checkpointed call of ``function`` (reference:
    paddle.distributed.fleet.utils.recompute)."""
    preserve_rng = kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", None)  # only the reentrant form exists here
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    needs_grad = _tracing.grad_enabled() and any(
        not t.stop_gradient for t in tensor_args)
    if not needs_grad:
        return function(*args, **kwargs)

    rng_before = default_generator.get_state() if preserve_rng else None
    # capture the ambient autocast state: backward runs OUTSIDE the user's
    # auto_cast block, but the re-forward must produce outputs of the same
    # dtypes as the original or the stored vjp rejects the cotangents
    amp_at_forward = _tracing.amp_state()

    class _Recompute(_autograd.PyLayer):
        @staticmethod
        def forward(ctx, *tensor_ins):
            out = function(*args, **kwargs)
            ctx._out_template = out
            return out

        @staticmethod
        def backward(ctx, *grads):
            # detached leaf copies of the tensor inputs collect input grads
            detached = [Tensor(t._data, stop_gradient=t.stop_gradient)
                        for t in tensor_args]
            it = iter(detached)
            re_args = tuple(next(it) if isinstance(a, Tensor) else a
                            for a in args)
            if rng_before is not None:
                rng_after = default_generator.get_state()
                default_generator.set_state(rng_before)
            # replay the forward's exact autocast state — including the
            # DISABLED state, so a backward() issued inside someone else's
            # auto_cast block can't re-cast the recomputation
            replay_amp = amp_at_forward if amp_at_forward is not None \
                else _tracing.AmpState(False, None, "O1", frozenset(),
                                       frozenset())
            _tracing.push_amp_state(replay_amp)
            try:
                with _tracing.enable_grad():
                    out = function(*re_args, **kwargs)
            finally:
                _tracing.pop_amp_state()
                if rng_before is not None:
                    default_generator.set_state(rng_after)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            outs = [o for o in outs if isinstance(o, Tensor)]
            gts = [g for o, g in zip(outs, grads)]
            _autograd.backward(list(outs), gts, retain_graph=False)
            import jax.numpy as jnp
            return tuple(
                d.grad if (d.grad is not None and not t.stop_gradient)
                else Tensor(jnp.zeros_like(t._data))
                for d, t in zip(detached, tensor_args))

    return _Recompute.apply(*tensor_args)


def recompute_sequential(ctx: dict, functions, *args, **kwargs):
    """Segment-wise recompute over an ``nn.Sequential`` (reference:
    recompute_sequential). ``ctx``: {"segments": N, "preserve_rng_state":…}."""
    segments = int(ctx.get("segments", 1))
    preserve = ctx.get("preserve_rng_state", True)
    layers = list(functions)
    step = max(1, len(layers) // segments)
    out = args
    for start in range(0, len(layers), step):
        chunk = layers[start:start + step]

        def run_chunk(*xs, _chunk=chunk):
            y = xs
            for sub in _chunk:
                y = sub(*y) if isinstance(y, tuple) else sub(y)
                y = y if isinstance(y, tuple) else (y,)
            return y if len(y) > 1 else y[0]

        out = recompute(run_chunk, *out, preserve_rng_state=preserve,
                        **kwargs)
        out = out if isinstance(out, tuple) else (out,)
    return out if len(out) > 1 else out[0]


class LocalFS:
    """Local filesystem client (parity: fleet.utils.LocalFS)."""

    def ls_dir(self, path):
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local, fs_path):
        shutil.copy(local, fs_path)

    def download(self, fs_path, local):
        shutil.copy(fs_path, local)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()


class HDFSClient:
    """Gated parity stub: HDFS access needs a cluster + hadoop binary; this
    zero-egress build raises with guidance (use LocalFS or mount the data)."""

    def __init__(self, hadoop_home=None, configs=None):
        raise RuntimeError(
            "HDFSClient is unavailable in this build (no hadoop runtime); "
            "use fleet.utils.LocalFS or mount the dataset locally.")


__all__ += ["HDFSClient"]
