"""Pipeline parallelism.

Parity surface: python/paddle/distributed/fleet/meta_parallel/
(``PipelineLayer`` with ``LayerDesc``/``SharedLayerDesc`` partitioning,
``PipelineParallel.train_batch`` with the 1F1B microbatch schedule,
p2p_communication).

TPU-native design notes: on an SPMD mesh the 1F1B schedule is a COMPILER
SCHEDULING concern — microbatch k's forward on stage s can overlap k-1's
backward on s+1 only if the program exposes them to XLA together. This
module provides:

* the PipelineLayer/LayerDesc partitioning surface (stage assignment,
  shared-weight descs) — full parity;
* ``PipelineParallel.train_batch`` — microbatch loop with gradient
  accumulation; numerically EXACTLY the 1F1B result (1F1B reorders
  microbatch work but accumulates the same gradients);
* for uniform decoder stacks, ``paddle_tpu.distributed.fleet.tpu_pipeline``
  runs the truly pipelined shard_map/ppermute schedule over the pp mesh axis
  inside one XLA program.
"""

from __future__ import annotations

import re
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ... import observability as _obs
from ...core.tensor import Tensor
from ...nn.layer import Layer
from ...ops.manipulation import split as split_op
from ..topology import get_hybrid_communicate_group

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel"]


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """A layer whose weights are shared between pipeline stages (e.g. tied
    embedding + lm head — upstream pp_utils shared weights with an allreduce;
    here the shared module object IS the same object in both stages)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers: Sequence[Union[Layer, LayerDesc, Callable]],
                 num_stages: Optional[int] = None, topology=None,
                 loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, **kwargs):
        super().__init__()
        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        self._seg_method = seg_method
        self._loss_fn = loss_fn
        self._descs = list(layers)
        self._shared: Dict[str, Layer] = {}
        built: List[Any] = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = d.build_layer()
                built.append((self._shared[d.layer_name], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            else:
                built.append((d, None))
        self._stage_bounds = self._segment(built, num_stages, seg_method)
        from ...nn.container import LayerList
        self.run_function = LayerList([l for l, _ in built if isinstance(l, Layer)])
        self._entries = built

    @staticmethod
    def _segment(built: List[Any], n_stages: int, method: str) -> List[int]:
        n_layers = len(built)
        if method.startswith("layer:"):
            # upstream parity: stages split AT the named block class —
            # every stage starts on a Name block (stage 0 additionally
            # owns the embedding-side prefix, the last runs to the end)
            name = method.split(":", 1)[1]
            idxs = [i for i, (layer, _f) in enumerate(built)
                    if type(layer).__name__ == name]
            if len(idxs) >= n_stages:
                starts = [idxs[round(k * len(idxs) / n_stages)]
                          for k in range(n_stages)]
                starts[0] = 0
                return starts + [n_layers]
            # fewer named blocks than stages: upstream's placement
            # contract cannot be honored — WARN + count instead of
            # silently handing back even cuts that ignore the named
            # blocks entirely (ADVICE r5; MIGRATING "seg_method
            # semantics" documents the actual placement contract)
            _obs.inc("pipeline.seg_method_fallbacks_total")
            warnings.warn(
                f"PipelineLayer: seg_method={method!r} found only "
                f"{len(idxs)} {name!r} block(s) but {n_stages} pipeline "
                f"stages need at least one each; falling back to "
                f"count-balanced stage cuts (upstream would split at "
                f"the named blocks)")
        base = n_layers // n_stages
        extra = n_layers % n_stages
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < extra else 0))
        return bounds

    def get_stage_layers(self, stage: int) -> List:
        lo, hi = self._stage_bounds[stage], self._stage_bounds[stage + 1]
        return self._entries[lo:hi]

    def stage_of_layer(self, idx: int) -> int:
        for s in range(self._num_stages):
            if self._stage_bounds[s] <= idx < self._stage_bounds[s + 1]:
                return s
        return self._num_stages - 1

    def forward(self, x):
        if getattr(self, "_engine", None) is not None:
            raise RuntimeError(
                "this PipelineLayer was consumed by the pipelined engine "
                "(its per-stage copies were stacked and released); call "
                "through the fleet.distributed_model wrapper instead")
        for layer, ffunc in self._entries:
            if ffunc is not None:
                x = ffunc(layer, x)
            elif isinstance(layer, Layer):
                x = layer(x)
            else:
                x = layer(x)
        return x

    @property
    def parameters_by_stage(self):
        out = []
        for s in range(self._num_stages):
            params = []
            for layer, _ in self.get_stage_layers(s):
                if isinstance(layer, Layer):
                    params.extend(layer.parameters())
            out.append(params)
        return out


class PipelineParallel(Layer):
    """Microbatch training driver (parity: meta_parallel PipelineParallel).

    Two execution paths:

    * **pipelined** (default when the PipelineLayer has a uniform block run
      and the hybrid mesh has a ``pp`` axis): per-stage block weights are
      stacked and sharded over ``pp`` and the whole microbatch schedule runs
      as one shard_map/ppermute program — real stage placement, activations
      hop stages on ICI (``fleet.tpu_pipeline.PipelinedStack``).
    * **grad-accumulation fallback** (non-uniform stacks): microbatch loop
      accumulating gradients. NOTE: this fallback does NOT place stages on
      devices — it reproduces only the accumulated-gradient numerics that a
      1F1B schedule would also produce, with no pipelining.
    """

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        cfg = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self._loss_fn = layers._loss_fn
        self._engine = None
        pp = self._hcg.get_pipe_parallel_world_size() if self._hcg else 1
        if pp > 1 and "pp" in getattr(self._hcg.mesh, "axis_names", ()):
            from .tpu_pipeline import (HeteroPipelinedStack,
                                       NonUniformStackError, PipelinedStack)
            v_chunks = max(int(cfg.get("virtual_pp_degree", 1)), 1)
            try:
                self._engine = PipelinedStack(
                    layers, self._hcg.mesh, axis="pp",
                    micro_batches=self.accumulate_steps,
                    remat=bool(cfg.get("remat", True)),
                    v_chunks=v_chunks)
            except NonUniformStackError as uniform_err:
                # round 5: non-uniform stacks get REAL stage placement too —
                # contiguous param-balanced stages as lax.switch branches in
                # the same ppermute scan (grad accumulation only as the
                # last resort, or on hetero_pipeline=False)
                import warnings
                if v_chunks > 1:
                    warnings.warn(
                        f"pipeline parallel (pp={pp}): "
                        f"virtual_pp_degree={v_chunks} needs a uniform run "
                        f"of {pp * v_chunks} stage-chunks and none exists "
                        f"({uniform_err}); interleaved placement is "
                        "dropped for this model.", stacklevel=2)
                try:
                    if not cfg.get("hetero_pipeline", True):
                        raise NonUniformStackError(
                            "hetero_pipeline disabled by pipeline_configs "
                            f"(uniform engine: {uniform_err})")
                    self._engine = HeteroPipelinedStack(
                        layers, self._hcg.mesh, axis="pp",
                        micro_batches=self.accumulate_steps,
                        remat=bool(cfg.get("remat", True)))
                except NonUniformStackError as e:
                    self._engine = None  # last resort: grad accumulation
                    warnings.warn(
                        f"pipeline parallel (pp={pp}): {e}. Falling back to "
                        "the grad-accumulation path — numerics match 1F1B "
                        "but stages are NOT placed on devices (no "
                        "pipelining).", stacklevel=2)

    def _dismantle_hetero(self, e) -> bool:
        """First-call shape validation rejected the stack: unpack the
        weights back into the original blocks and fall back to grad
        accumulation — the pre-round-5 behavior for shape-changing stacks
        (numerics match 1F1B, no stage placement). Optimizers built from
        wrapped.parameters() (the fused buffers) must be rebuilt;
        optimizers built from the ORIGINAL layer's parameters keep
        working. Validation runs before any compute, so retrying the same
        batch on the fallback is safe."""
        from .tpu_pipeline import HeteroPipelinedStack
        if not isinstance(self._engine, HeteroPipelinedStack):
            return False
        import warnings
        warnings.warn(
            f"pipeline parallel: {e}. Dismantled the hetero engine; "
            "continuing on the grad-accumulation fallback.", stacklevel=3)
        self._engine.dismantle()
        self._engine = None
        return True

    def forward(self, *args, **kwargs):
        if self._engine is not None:
            from .tpu_pipeline import NonUniformStackError
            try:
                return self._engine(*args, **kwargs)
            except NonUniformStackError as e:
                if not self._dismantle_hetero(e):
                    raise
        return self._layers(*args, **kwargs)

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            xs = [self._split_micro(d) for d in data]
            return list(zip(*xs))
        n = self.accumulate_steps
        if self.micro_batch_size is not None:
            n = max(data.shape[0] // int(self.micro_batch_size), 1)
            self.accumulate_steps = n
        return split_op(data, n, axis=0)

    def train_batch(self, data, optimizer=None, lr_scheduler=None, scaler=None):
        self._layers.train()
        if self._engine is not None:
            from .tpu_pipeline import NonUniformStackError
            try:
                return self._train_batch_pipelined(data, optimizer,
                                                   lr_scheduler, scaler)
            except NonUniformStackError as e:
                if not self._dismantle_hetero(e):
                    raise  # falls through to the grad-accum loop below
        micros = self._split_micro(data)
        n = len(micros)
        total = None
        for mb in micros:
            if isinstance(mb, (tuple, list)):
                x, label = mb[0], mb[1]
            else:
                x, label = mb, None
            out = self._layers(x)
            loss = self._loss_fn(out, label) if self._loss_fn is not None else out
            scaled = loss * (1.0 / n)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = loss if total is None else total + loss
        if optimizer is not None:
            if scaler is not None:
                scaler.step(optimizer)
                scaler.update()
            else:
                optimizer.step()
            optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total * (1.0 / n)

    def _train_batch_pipelined(self, data, optimizer=None, lr_scheduler=None,
                               scaler=None):
        if isinstance(data, (tuple, list)):
            x, label = data[0], data[1]
        else:
            x, label = data, None
        self._engine._M = self.accumulate_steps
        if self.micro_batch_size is not None:
            self._engine._M = max(
                int(x.shape[0]) // int(self.micro_batch_size), 1)
            self.accumulate_steps = self._engine._M
        out = self._engine(x)
        loss = self._loss_fn(out, label) if self._loss_fn is not None else out
        if scaler is not None:
            scaler.scale(loss).backward()
        else:
            loss.backward()
        if optimizer is not None:
            if scaler is not None:
                scaler.step(optimizer)
                scaler.update()
            else:
                optimizer.step()
            optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss: bool = True):
        self._layers.eval()
        if self._engine is not None:
            from ...core.tracing import no_grad
            with no_grad():
                if isinstance(data, (tuple, list)):
                    x, label = data[0], data[1]
                else:
                    x, label = data, None
                # eval has no microbatching requirement; a single microbatch
                # always divides the batch
                out = self._engine(x, micro_batches=1)
                if compute_loss and self._loss_fn is not None:
                    return self._loss_fn(out, label)
                return out
        micros = self._split_micro(data)
        outs = []
        from ...core.tracing import no_grad
        with no_grad():
            for mb in micros:
                if isinstance(mb, (tuple, list)):
                    x, label = mb[0], mb[1]
                else:
                    x, label = mb, None
                out = self._layers(x)
                if compute_loss and self._loss_fn is not None:
                    out = self._loss_fn(out, label)
                outs.append(out)
        if compute_loss:
            total = outs[0]
            for o in outs[1:]:
                total = total + o
            return total * (1.0 / len(outs))
        return outs

    def parameters(self, include_sublayers=True):
        if self._engine is not None:
            return self._engine.parameters()
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        if self._engine is not None:
            return self._engine.state_dict()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        if self._engine is not None:
            return self._engine.set_state_dict(sd)
        return self._layers.set_state_dict(sd, *a, **k)
