"""Fleet facade.

Parity surface: python/paddle/distributed/fleet/ (``fleet.init``,
``DistributedStrategy``, ``fleet.distributed_model``,
``fleet.distributed_optimizer``, RoleMaker). TPU-native: ``init`` builds the
HybridCommunicateGroup → one jax Mesh; ``distributed_model`` wraps for
dp/pp; TP layers (mp_layers) are sharded-storage layers that need no
wrapping; ``distributed_optimizer`` applies sharding stages.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..env import init_parallel_env
from ..topology import (HybridCommunicateGroup, get_hybrid_communicate_group,
                        set_hybrid_communicate_group)
from .strategy import DistributedStrategy
from . import elastic  # noqa: F401
from . import mp_layers  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .mp_layers import (ColumnParallelLinear, RowParallelLinear,  # noqa: F401
                        VocabParallelEmbedding, ParallelCrossEntropy)
from .role_maker import (Role, RoleMakerBase,  # noqa: F401
                         PaddleCloudRoleMaker, UserDefinedRoleMaker)

__all__ = [
    "init", "DistributedStrategy", "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group", "HybridCommunicateGroup",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "PipelineLayer", "LayerDesc", "SharedLayerDesc",
    "Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
    "is_server", "is_worker", "is_first_worker", "worker_index", "worker_num",
    "server_num", "worker_endpoints", "server_endpoints", "init_server",
    "run_server", "init_worker", "stop_worker", "barrier_worker",
    "get_communicator",
]

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None
_role_maker = None
_server_store = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    """``fleet.init`` parity: parse the hybrid config, build the mesh.

    PS mode (``role_maker`` given, not collective): SERVER processes host
    only the coordination KV plane (the sparse tables themselves are
    mesh-sharded dense tensors on the workers — the north-star "PS → ICI
    allreduce path"); WORKER processes form the collective training world.
    """
    global _fleet_initialized, _strategy, _role_maker
    # PS mode when the role maker carries PS structure (server role or server
    # endpoints) OR the caller said not collective — upstream defaults
    # is_collective=False, so a ported `fleet.init(PaddleCloudRoleMaker())`
    # with PS env vars must land here even with our collective-first default
    ps_mode = role_maker is not None and (
        not is_collective or role_maker.is_server()
        or role_maker.server_num() > 0)
    if ps_mode:
        _role_maker = role_maker
        _strategy = strategy or DistributedStrategy()
        _fleet_initialized = True
        if role_maker.is_server():
            # servers never join the collective mesh; their lifecycle is
            # init_server()/run_server()
            return
        # PS-mode workers are NOT one SPMD world: each drives its own
        # device(s) and exchanges through the table plane (reference: async
        # trainers against brpc tables). Build the local topology only.
        from ..topology import _ensure_default_topology
        _ensure_default_topology()
        return
    init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    hc = _strategy.hybrid_configs
    from ... import device as _device
    ndev = len(_device.get_all_devices())
    degrees = {
        "dp": hc.get("dp_degree", -1),
        "mp": hc.get("mp_degree", 1),
        "pp": hc.get("pp_degree", 1),
        "sharding": hc.get("sharding_degree", 1),
        "sep": hc.get("sep_degree", 1),
    }
    fixed = 1
    for k, v in degrees.items():
        if k != "dp" and v > 1:
            fixed *= v
    if degrees["dp"] == -1:
        degrees["dp"] = max(ndev // fixed, 1)
    HybridCommunicateGroup(
        dp_degree=degrees["dp"], mp_degree=degrees["mp"],
        pp_degree=degrees["pp"], sharding_degree=degrees["sharding"],
        sep_degree=degrees["sep"])
    _fleet_initialized = True
    return


def is_initialized() -> bool:
    return _fleet_initialized


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy


# --- PS-mode surface (parity: fleet_base's PS lifecycle; backed by the
# ICI sharded-embedding path, so "servers" host only the KV/rendezvous plane
# — see distributed/sharded_embedding.py for where the tables actually live)

def _rm():
    if _role_maker is None:
        raise RuntimeError("fleet.init(role_maker) was not called (PS mode)")
    return _role_maker


def is_server() -> bool:
    return _role_maker is not None and _role_maker.is_server()


def is_worker() -> bool:
    return _role_maker is None or _role_maker.is_worker()


def is_first_worker() -> bool:
    return _role_maker is None or _role_maker.is_first_worker()


def worker_index() -> int:
    return 0 if _role_maker is None else _role_maker.worker_index()


def worker_num() -> int:
    return 1 if _role_maker is None else _role_maker.worker_num()


def server_num() -> int:
    return 0 if _role_maker is None else _role_maker.server_num()


def worker_endpoints(to_string: bool = False):
    eps = [] if _role_maker is None else _role_maker.get_trainer_endpoints()
    return ",".join(eps) if to_string else eps


def server_endpoints(to_string: bool = False):
    eps = [] if _role_maker is None else _role_maker.get_pserver_endpoints()
    return ",".join(eps) if to_string else eps


def _ps_rpc_endpoint(rm) -> str:
    """The PS RPC plane rides the first server's endpoint shifted by one
    port (the server endpoint itself is the shutdown-coordination store)."""
    host, _, port = rm.get_pserver_endpoints()[0].rpartition(":")
    return f"{host or '127.0.0.1'}:{int(port) + 1}"


def init_server(*args, use_ps_service: bool = False,
                recover_dir: Optional[str] = None, **kwargs) -> None:
    """Start this server's KV plane (reference: BrpcPsServer startup loading
    table shards). ``use_ps_service=True`` additionally joins the job RPC
    plane and HOSTS TABLE STATE in this process (``distributed.ps_service``)
    — workers then push (rows, values) sparse grads across the process
    boundary instead of mutating mesh-local tables. ``recover_dir``: load
    this server's shard snapshot (``<dir>/shard_<index>``) BEFORE joining
    the RPC plane, so a respawned server never serves an empty table to a
    worker whose push raced the operator's recovery call (upstream:
    PServer startup table load)."""
    global _server_store
    import os as _os
    from ..store import TCPStore
    rm = _rm()
    ep = rm.get_pserver_endpoints()[rm.server_index()]
    port = int(ep.rsplit(":", 1)[1])
    _server_store = TCPStore(is_master=True, port=port,
                             world_size=rm.worker_num())
    if use_ps_service:
        from .. import rpc as _rpc
        from .. import ps_service
        ps_service.reset_server_state()
        idx = rm.server_index()
        if recover_dir:
            shard = _os.path.join(recover_dir, f"shard_{idx}")
            if _os.path.isdir(shard):
                ps_service._srv_load(shard)
        _rpc.init_rpc(f"ps/{idx}", rank=idx,
                      world_size=rm.server_num() + rm.worker_num(),
                      master_endpoint=_ps_rpc_endpoint(rm))


def run_server() -> None:
    """Serve until every worker has called ``stop_worker`` (reference:
    brpc server loop until shutdown RPCs arrive). ``add(key, 0)`` is the
    atomic counter read."""
    import time as _time
    rm = _rm()
    if _server_store is None:
        init_server()
    while True:
        try:
            if _server_store.add("ps/shutdown", 0) >= rm.worker_num():
                break
        except TimeoutError:
            pass  # transient: keep serving
        # ConnectionError and friends propagate — the store lives in THIS
        # process, so a broken store is fatal, and a silent spin here would
        # mask the failure behind the launcher's kill timeout
        _time.sleep(0.2)
    _server_store.close()


def init_worker(scopes=None) -> None:
    """Reference: creates the brpc client + pulls dense params and starts
    the async Communicator. ICI path: tables are mesh-resident; when the
    strategy asks for a_sync, a ``distributed.communicator.Communicator``
    starts so ``push_sparse`` hands updates to a background applier
    (upstream Communicator::Start)."""
    global _communicator
    rm = _rm()  # assert PS mode
    st = get_strategy()
    if st is not None and getattr(st, "a_sync", False):
        if _communicator is not None:  # re-init (elastic restart): replace
            _communicator.stop()
        from ..communicator import Communicator, registered_tables
        cfg = getattr(st, "a_sync_configs", {}) or {}
        mode = "geo" if int(cfg.get("k_steps", 0) or 0) > 0 else "async"
        remote = None
        if cfg.get("use_ps_service"):
            # cross-process PS: join the RPC plane and aim pushes at the
            # table-hosting server process (reference BrpcPsClient)
            from .. import rpc as _rpc
            from ..ps_service import PsClient
            widx = rm.worker_index()
            _rpc.init_rpc(f"worker/{widx}", rank=rm.server_num() + widx,
                          world_size=rm.server_num() + rm.worker_num(),
                          master_endpoint=_ps_rpc_endpoint(rm))
            # round 5: all servers — hash sparse tables shard by
            # id % server_num across them (dense tables stay on ps/0)
            remote = PsClient([f"ps/{i}" for i in range(rm.server_num())])
        _communicator = Communicator(
            mode=mode, geo_k=int(cfg.get("k_steps", 0) or 8),
            send_queue_size=int(cfg.get("send_queue_size", 32) or 32),
            remote=remote)
        # every live ShardedEmbedding table is a push/pull target
        _communicator.init_with_ctx(registered_tables())
        _communicator.start()


_communicator = None


def get_communicator():
    """The worker's active async Communicator (None in sync mode)."""
    return _communicator


def stop_worker() -> None:
    """Signal every server's KV plane that this worker is done."""
    global _communicator
    if _communicator is not None:
        _communicator.barrier()
        _communicator.stop()
        _communicator = None
    from ..store import TCPStore
    rm = _rm()
    for ep in rm.get_pserver_endpoints():
        host, port = ep.rsplit(":", 1)
        try:
            c = TCPStore(host or "127.0.0.1", int(port))
            c.add("ps/shutdown", 1)
            c.close()
        except Exception:
            pass  # server already gone


def barrier_worker() -> None:
    """Barrier across worker processes (uses the collective env when
    multi-process; trivially passes single-process)."""
    import jax
    if jax.process_count() > 1:
        from ..comm import barrier
        barrier()


def distributed_model(model):
    """Wrap per active parallelism (parity:
    python/paddle/distributed/fleet/base/fleet_base.py distributed_model)."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        init()
        hcg = get_hybrid_communicate_group()
    from .pipeline_parallel import PipelineLayer, PipelineParallel
    if hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError(
                "pp_degree > 1 requires the model to be a fleet PipelineLayer")
        return PipelineParallel(model, hcg, get_strategy())
    if hcg.get_data_parallel_world_size() > 1:
        from ..parallel import DataParallel
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Apply hybrid/sharding wrappers (parity: HybridParallelOptimizer /
    DygraphShardingOptimizer selection in fleet_base)."""
    hcg = get_hybrid_communicate_group()
    st = strategy or _strategy or DistributedStrategy()
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        from ..sharding.sharding_optimizer import DygraphShardingOptimizer
        stage = st.hybrid_configs.get("sharding_configs", {}).get("stage", 1)
        return DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    return optimizer


# surface the PP classes at fleet namespace parity locations
from .pipeline_parallel import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401,E402


class UtilBase:
    def all_reduce(self, input, mode="sum"):
        from .. import all_reduce as _ar
        return _ar(input)


util = UtilBase()

from . import utils  # noqa: F401,E402  (fleet.utils: recompute, LocalFS)

from . import meta_parallel  # noqa: F401,E402
from . import meta_optimizers  # noqa: F401,E402
from . import mp_layers as layers  # noqa: F401,E402  (fleet.layers.mpu parity)


def model(m):
    """Parity alias: fleet.model == fleet.distributed_model."""
    return distributed_model(m)


def optimizer(opt, strategy=None):
    """Parity alias: fleet.optimizer == fleet.distributed_optimizer."""
    return distributed_optimizer(opt, strategy)


def distributed_scaler(scaler):
    """Wrap an amp GradScaler for hybrid parallel (parity:
    fleet.distributed_scaler). Gradient collectives already ride the mesh
    inside the compiled step, so the scaler's found_inf aggregation is the
    only distributed concern — all_reduce folds it across ranks."""
    return scaler
