"""Fleet facade.

Parity surface: python/paddle/distributed/fleet/ (``fleet.init``,
``DistributedStrategy``, ``fleet.distributed_model``,
``fleet.distributed_optimizer``, RoleMaker). TPU-native: ``init`` builds the
HybridCommunicateGroup → one jax Mesh; ``distributed_model`` wraps for
dp/pp; TP layers (mp_layers) are sharded-storage layers that need no
wrapping; ``distributed_optimizer`` applies sharding stages.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from ..env import init_parallel_env
from ..topology import (HybridCommunicateGroup, get_hybrid_communicate_group,
                        set_hybrid_communicate_group)
from .strategy import DistributedStrategy
from . import mp_layers  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .mp_layers import (ColumnParallelLinear, RowParallelLinear,  # noqa: F401
                        VocabParallelEmbedding, ParallelCrossEntropy)

__all__ = [
    "init", "DistributedStrategy", "distributed_model", "distributed_optimizer",
    "get_hybrid_communicate_group", "HybridCommunicateGroup",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "PipelineLayer", "LayerDesc", "SharedLayerDesc",
]

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    """``fleet.init`` parity: parse the hybrid config, build the mesh."""
    global _fleet_initialized, _strategy
    init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    hc = _strategy.hybrid_configs
    import jax
    ndev = len(jax.devices())
    degrees = {
        "dp": hc.get("dp_degree", -1),
        "mp": hc.get("mp_degree", 1),
        "pp": hc.get("pp_degree", 1),
        "sharding": hc.get("sharding_degree", 1),
        "sep": hc.get("sep_degree", 1),
    }
    fixed = 1
    for k, v in degrees.items():
        if k != "dp" and v > 1:
            fixed *= v
    if degrees["dp"] == -1:
        degrees["dp"] = max(ndev // fixed, 1)
    HybridCommunicateGroup(
        dp_degree=degrees["dp"], mp_degree=degrees["mp"],
        pp_degree=degrees["pp"], sharding_degree=degrees["sharding"],
        sep_degree=degrees["sep"])
    _fleet_initialized = True
    return


def is_initialized() -> bool:
    return _fleet_initialized


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy


def distributed_model(model):
    """Wrap per active parallelism (parity:
    python/paddle/distributed/fleet/base/fleet_base.py distributed_model)."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        init()
        hcg = get_hybrid_communicate_group()
    from .pipeline_parallel import PipelineLayer, PipelineParallel
    if hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError(
                "pp_degree > 1 requires the model to be a fleet PipelineLayer")
        return PipelineParallel(model, hcg, get_strategy())
    if hcg.get_data_parallel_world_size() > 1:
        from ..parallel import DataParallel
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Apply hybrid/sharding wrappers (parity: HybridParallelOptimizer /
    DygraphShardingOptimizer selection in fleet_base)."""
    hcg = get_hybrid_communicate_group()
    st = strategy or _strategy or DistributedStrategy()
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        from ..sharding.sharding_optimizer import DygraphShardingOptimizer
        stage = st.hybrid_configs.get("sharding_configs", {}).get("stage", 1)
        return DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    return optimizer


# surface the PP classes at fleet namespace parity locations
from .pipeline_parallel import PipelineLayer, LayerDesc, SharedLayerDesc  # noqa: F401,E402


class UtilBase:
    def all_reduce(self, input, mode="sum"):
        from .. import all_reduce as _ar
        return _ar(input)


util = UtilBase()
