"""``paddle.distributed.fleet.meta_optimizers`` namespace (reference:
python/paddle/distributed/fleet/meta_optimizers/) — the dygraph sharding
optimizer lives here upstream; the hybrid-parallel wrapping is
``fleet.distributed_optimizer``'s job in this build."""

from ..sharding import DygraphShardingOptimizer  # noqa: F401

__all__ = ["DygraphShardingOptimizer"]
