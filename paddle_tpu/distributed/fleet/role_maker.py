"""RoleMaker: process identity for fleet PS-mode jobs.

Parity surface: python/paddle/distributed/fleet/base/role_maker.py
(``Role``, ``PaddleCloudRoleMaker`` parsing the PADDLE_* env contract,
``UserDefinedRoleMaker``). The reference uses these to split a job into
brpc parameter-server processes and trainer processes (upstream
paddle/fluid/distributed/ps/service/).

TPU-native meaning (north star: "PS → ICI allreduce path"): the embedding
table is mesh-sharded (distributed.sharded_embedding) and updated by XLA
collectives over ICI, so SERVER processes host only the rendezvous/KV plane
(our TCPStore), not parameter shards; WORKER processes form the collective
training world. The API shape (is_server/is_worker/worker_num/...) is kept
so PaddleRec-style training scripts port unchanged.
"""

from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self):
        self._role: Optional[int] = None
        self._current_id: int = 0
        self._worker_endpoints: List[str] = []
        self._server_endpoints: List[str] = []

    # --- identity ----------------------------------------------------------
    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self._role == Role.WORKER and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id if self._role == Role.WORKER else -1

    def server_index(self) -> int:
        return self._current_id if self._role == Role.SERVER else -1

    def role_id(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return max(len(self._worker_endpoints), 1)

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def get_trainer_endpoints(self) -> List[str]:
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self) -> List[str]:
        return list(self._server_endpoints)

    def to_string(self) -> str:
        return (f"role={self._role} id={self._current_id} "
                f"workers={self._worker_endpoints} "
                f"servers={self._server_endpoints}")


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-contract role maker (the launcher/PaddleCloud sets PADDLE_*)."""

    def __init__(self, is_collective: bool = False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        if is_collective:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = [e for e in eps.split(",") if e]
            return
        training_role = os.environ.get("TRAINING_ROLE",
                                       os.environ.get("PADDLE_TRAINING_ROLE",
                                                      "TRAINER"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        seps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                              os.environ.get("PADDLE_PORT", ""))
        self._server_endpoints = [e for e in seps.split(",") if e]
        if training_role in ("TRAINER", "WORKER"):
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        elif training_role == "PSERVER":
            self._role = Role.SERVER
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
            self._current_id = (self._server_endpoints.index(cur)
                                if cur in self._server_endpoints else 0)
        else:
            raise ValueError(f"unknown TRAINING_ROLE {training_role!r}")


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicitly-specified role (parity: fleet.UserDefinedRoleMaker)."""

    def __init__(self, is_collective: bool = False, current_id: int = 0,
                 role: int = Role.WORKER, worker_num: int = 1,
                 server_endpoints: Optional[List[str]] = None,
                 worker_endpoints: Optional[List[str]] = None, **kwargs):
        super().__init__()
        self._role = role
        self._current_id = current_id
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(
            worker_endpoints or [""] * worker_num)
