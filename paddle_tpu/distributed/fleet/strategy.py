"""DistributedStrategy.

Parity surface: the reference's protobuf-backed DistributedStrategy
(upstream paddle/fluid/framework/distributed_strategy.proto + python facade
python/paddle/distributed/fleet/base/distributed_strategy.py). TPU-native:
a typed dataclass tree serialized to JSON (SURVEY.md §5 config design) —
same nested strategy surface (hybrid_configs, sharding_configs, amp_configs,
recompute_configs...), no protobuf dependency.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict


def _default_hybrid() -> Dict[str, Any]:
    return {
        "dp_degree": -1, "mp_degree": 1, "pp_degree": 1,
        "sharding_degree": 1, "sep_degree": 1,
        "order": ["dp", "pp", "sharding", "sep", "mp"],
        "mp_configs": {}, "pp_configs": {}, "sharding_configs": {"stage": 1},
    }


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = _default_hybrid()
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 65536.0, "custom_white_list": [],
            "custom_black_list": [], "use_pure_fp16": False,
            "use_bf16": True, "level": "O1",
        }
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {
            "stage": 1, "degree": 1, "offload": False,
        }
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {
            "accumulate_steps": 1, "micro_batch_size": 1,
            "schedule_mode": "1F1B",
        }
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.dgc = False
        self.a_sync = False  # PS mode toggle (parity)
        self.a_sync_configs: Dict[str, Any] = {}
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True  # accepted; XLA fuses natively
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1  # accepted no-op: ICI has no comm objects

    # hybrid_configs is settable with a partial dict (paddle behavior)
    def __setattr__(self, key, value):
        if key == "hybrid_configs" and isinstance(value, dict) and \
                getattr(self, "hybrid_configs", None):
            merged = dict(self.hybrid_configs)
            merged.update(value)
            object.__setattr__(self, key, merged)
        else:
            object.__setattr__(self, key, value)

    def to_json(self) -> str:
        return json.dumps({k: v for k, v in self.__dict__.items()},
                          default=str, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "DistributedStrategy":
        st = cls()
        st.__dict__.update(json.loads(s))
        return st

    def save_to_prototxt(self, path: str) -> None:  # parity name
        with open(path, "w") as f:
            f.write(self.to_json())

    def load_from_prototxt(self, path: str) -> None:
        with open(path) as f:
            self.__dict__.update(json.loads(f.read()))

    def __repr__(self):
        return "DistributedStrategy(" + self.to_json() + ")"
