"""Tensor-parallel (mp) layers.

Parity surface: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
ParallelCrossEntropy) + mp_ops.py (c_identity/c_concat/mp_allreduce ops).

TPU-native design (SURVEY.md §7.4): the weights are FULL logical arrays whose
storage is sharded over the ``mp`` mesh axis via NamedSharding — forward is a
plain matmul with sharding constraints, and XLA inserts the column/row
collectives (identity / all-gather / psum) itself. No hand-written c_* comm
ops; the same layer code runs eagerly (SPMD eager) and under to_static.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.random import Generator, default_generator
from ...core.tensor import Tensor, apply
from ...nn import functional as F
from ...nn.initializer import XavierUniform
from ...nn.layer import Layer
from ..topology import get_hybrid_communicate_group

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "get_rng_state_tracker", "RNGStatesTracker",
]


def _mp_mesh():
    hcg = get_hybrid_communicate_group()
    if hcg is None or hcg.get_model_parallel_world_size() <= 1:
        return None, None
    return hcg.mesh, "mp"


def _shard_param(p: Tensor, spec) -> Tensor:
    mesh, axis = _mp_mesh()
    if mesh is not None:
        p._set_data(jax.device_put(p._data, NamedSharding(mesh, spec)))
    return p


def _constrain(t: Tensor, spec) -> Tensor:
    """Apply a sharding constraint (works eagerly and under tracing).

    Inside a PARTIAL-manual shard_map (the pipeline engine maps pp/dp
    manually and leaves mp auto), the constraint must be expressed on the
    ambient ABSTRACT mesh — whose axis types mark pp/dp Manual — not the
    concrete all-auto mesh, or jax rejects the manual vma axes."""
    mesh, _ = _mp_mesh()
    if mesh is None:
        return t

    def f(a):
        use = mesh
        try:
            cur = jax.sharding.get_abstract_mesh()
            if cur is not None and cur.axis_names:
                use = cur
        except Exception:
            pass  # no abstract mesh in scope: constrain on the concrete one
        return jax.lax.with_sharding_constraint(a, NamedSharding(use, spec))

    return apply("sharding_constraint", f, t)


class ColumnParallelLinear(Layer):
    """Y = XW, W (in, out) column-sharded over mp; X replicated."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, bias_attr=None, name=None):
        super().__init__()
        if bias_attr is False:
            has_bias = False
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.is_mp = _mp_mesh()[0] is not None
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.is_distributed = True
        _shard_param(self.weight, P(None, "mp"))
        if has_bias:
            self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                              is_bias=True)
            self.bias.is_distributed = True
            _shard_param(self.bias, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        nd = out._data.ndim
        if self.is_mp:
            if self.gather_output:
                out = _constrain(out, P(*([None] * nd)))
            else:
                out = _constrain(out, P(*([None] * (nd - 1)), "mp"))
        return out


class RowParallelLinear(Layer):
    """Y = XW, W (in, out) row-sharded over mp; X arrives sharded on its last
    dim when ``input_is_parallel`` (the XLA-psum pairs with an upstream
    ColumnParallelLinear(gather_output=False))."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, bias_attr=None, name=None):
        super().__init__()
        if bias_attr is False:
            has_bias = False
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = _mp_mesh()[0] is not None
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.is_distributed = True
        _shard_param(self.weight, P("mp", None))
        if has_bias:
            # bias applied AFTER the reduction: replicated
            self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                              is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.is_mp and self.input_is_parallel:
            nd = x._data.ndim
            x = _constrain(x, P(*([None] * (nd - 1)), "mp"))
        out = F.linear(x, self.weight)
        if self.is_mp:
            nd = out._data.ndim
            out = _constrain(out, P(*([None] * nd)))  # forces the psum
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dimension sharded over mp."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.is_mp = _mp_mesh()[0] is not None
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.is_distributed = True
        _shard_param(self.weight, P("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        if self.is_mp:
            nd = out._data.ndim
            out = _constrain(out, P(*([None] * nd)))
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over vocab-sharded logits (parity:
    mpu ParallelCrossEntropy; the reference does a custom comm softmax —
    XLA derives the same reduce pattern from the shardings)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class RNGStatesTracker:
    """Per-name RNG streams (parity: fleet/layers/mpu/random.py — the
    model-parallel RNG tracker that keeps dropout identical across mp ranks
    for replicated activations and distinct for sharded ones)."""

    def __init__(self):
        self._states = {}

    def add(self, name: str, seed: int) -> None:
        if name in self._states:
            raise ValueError(f"state {name!r} already exists")
        self._states[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states) -> None:
        self._states = dict(states)

    def rng_state(self, name: str = "model_parallel_rng"):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            if name not in self._states:
                self.add(name, hash(name) % (2 ** 31))
            gen = self._states[name]
            saved = default_generator._key._data
            default_generator._key._set_data(gen._key._data)
            try:
                yield
            finally:
                gen._key._set_data(default_generator._key._data)
                default_generator._key._set_data(saved)

        return _ctx()


_rng_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _rng_tracker
