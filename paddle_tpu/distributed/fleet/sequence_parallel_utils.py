"""Megatron-style sequence parallelism utilities.

Parity surface: python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py (ScatterOp, GatherOp, AllGatherOp,
ReduceScatterOp, ColumnSequenceParallelLinear, RowSequenceParallelLinear,
register_sequence_parallel_allreduce_hooks).

TPU-native design (SURVEY.md §5 long-context item 1): "scatter" and
"gather" are sharding constraints on the SEQUENCE dimension over the mp
axis — outside attention/FFN blocks activations live seq-sharded (memory /
mp_degree), and XLA materializes the all-gather/reduce-scatter exactly where
the constraints flip, which is the Megatron SP communication pattern.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor, apply
from ...nn import functional as F
from ...nn.initializer import XavierUniform
from ...nn.layer import Layer
from ..topology import get_hybrid_communicate_group
from .mp_layers import _constrain, _mp_mesh, _shard_param

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather",
    "mark_as_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
]

# activations are (B, L, H): the sequence axis is dim 1 (paddle SP uses dim 0
# of (L, B, H) upstream; we keep batch-first and document the difference)
_SEQ_DIM = 1


def scatter(x: Tensor, seq_dim: int = _SEQ_DIM) -> Tensor:
    """Shard the sequence dim over mp (paddle ScatterOp.forward)."""
    mesh, axis = _mp_mesh()
    if mesh is None:
        return x
    spec = [None] * x._data.ndim
    spec[seq_dim] = "mp"
    return _constrain(x, P(*spec))


def all_gather(x: Tensor, seq_dim: int = _SEQ_DIM) -> Tensor:
    """Replicate the sequence dim (paddle GatherOp / AllGatherOp)."""
    mesh, axis = _mp_mesh()
    if mesh is None:
        return x
    return _constrain(x, P(*([None] * x._data.ndim)))


class ScatterOp:
    @staticmethod
    def apply(x, seq_dim: int = _SEQ_DIM):
        return scatter(x, seq_dim)


class GatherOp:
    @staticmethod
    def apply(x, seq_dim: int = _SEQ_DIM):
        return all_gather(x, seq_dim)


class AllGatherOp(GatherOp):
    pass


class ReduceScatterOp:
    @staticmethod
    def apply(x, seq_dim: int = _SEQ_DIM):
        # sum-over-mp then scatter the seq dim: with sharded matmul inputs the
        # partial-sum + constraint lowers to one reduce-scatter in XLA
        return scatter(x, seq_dim)


def mark_as_sequence_parallel_parameter(param: Tensor) -> None:
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model: Layer, accumulation_steps=1,
                                               fuse_allreduce=True) -> None:
    """Parity shim: grads of sequence-parallel params (layernorms living on
    seq-sharded activations) need an mp all-reduce in the reference; with
    sharding constraints XLA already emits the correct grad collectives, so
    this registers nothing and exists for API compatibility."""


class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear whose INPUT is sequence-sharded: the implicit
    all-gather of the sequence happens at the matmul (XLA materializes it
    from the sharding flip)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.is_mp = _mp_mesh()[0] is not None
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.is_distributed = True
        _shard_param(self.weight, P(None, "mp"))
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias else None
        if self.bias is not None:
            _shard_param(self.bias, P("mp"))

    def forward(self, x):
        x = all_gather(x)  # seq-sharded -> full sequence at the matmul
        out = F.linear(x, self.weight, self.bias)
        if self.is_mp and not self.gather_output:
            nd = out._data.ndim
            out = _constrain(out, P(*([None] * (nd - 1)), "mp"))
        return out


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear whose OUTPUT is sequence-sharded: the psum over
    mp becomes a reduce-scatter onto the sequence dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.is_mp = _mp_mesh()[0] is not None
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.is_distributed = True
        _shard_param(self.weight, P("mp", None))
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias else None

    def forward(self, x):
        if self.is_mp and self.input_is_parallel:
            nd = x._data.ndim
            x = _constrain(x, P(*([None] * (nd - 1)), "mp"))
        out = F.linear(x, self.weight)
        out = scatter(out)  # reduce-scatter: sum over mp + shard seq dim
        if self.bias is not None:
            out = out + self.bias
        return out
