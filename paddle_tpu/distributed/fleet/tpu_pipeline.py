"""Compiled pipeline parallelism over the ``pp`` mesh axis.

The truly-pipelined schedule (SURVEY.md §7 hard-part #1): for a UNIFORM stack
of blocks (the transformer case), per-stage parameters are stacked along a
leading axis sharded over ``pp``; one ``shard_map`` program runs the GPipe
schedule — a ``lax.scan`` over M + S - 1 ticks where every stage computes a
different microbatch each tick and activations hop stages with
``lax.ppermute``. XLA overlaps the ppermute with the next tick's compute
(async collective permute on ICI), which is exactly what the reference's
p2p_communication + 1F1B scheduling achieves with NCCL streams. Backward is
jax AD through the scan; ``jax.checkpoint`` on the stage body gives 1F1B's
activation-memory profile (only per-tick boundaries are stored).

Use through ``pipelined_forward`` (functional) or wire stacked params from a
PipelineLayer of identical LayerDescs.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["pipelined_forward", "stack_stage_params"]


def stack_stage_params(per_stage_params, mesh: Mesh, axis: str = "pp"):
    """Stack a list of S per-stage param pytrees along a new leading axis and
    shard it over ``axis``."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                     *per_stage_params)

    def place(a):
        spec = P(axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked)


def pipelined_forward(stage_fn: Callable, stacked_params, micro_inputs,
                      mesh: Mesh, axis: str = "pp", remat: bool = True):
    """Run the GPipe schedule.

    stage_fn(stage_params, x) -> y       one stage's computation
    stacked_params: pytree, leaves (S, ...) sharded over ``axis``
    micro_inputs:   (M, B_mb, ...) microbatched input (replicated)
    returns         (M, B_mb, ...) outputs of the last stage
    """
    S = int(mesh.shape[axis])
    M = micro_inputs.shape[0]
    T = M + S - 1
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def local_fn(params_local, micro):
        # params_local leaves: (1, ...) — this stage's slice
        p_mine = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def vary(x):
            return jax.lax.pcast(x, axis, to="varying")

        act0 = vary(jnp.zeros_like(micro[0]))
        out_buf0 = vary(jnp.zeros((M,) + micro.shape[1:], micro.dtype))

        def tick(carry, t):
            act_in, out_buf = carry
            # stage 0 ingests microbatch t; later stages use the hopped act
            mb_idx = jnp.clip(t, 0, M - 1)
            x = jnp.where(stage == 0, micro[mb_idx], act_in)
            y = body(p_mine, x)
            # last stage records microbatch (t - S + 1) when it's valid
            rec = t - (S - 1)
            valid = jnp.logical_and(stage == S - 1,
                                    jnp.logical_and(rec >= 0, rec < M))
            out_buf = jax.lax.cond(
                valid,
                lambda ob: jax.lax.dynamic_update_index_in_dim(
                    ob, y, jnp.clip(rec, 0, M - 1), axis=0),
                lambda ob: ob, out_buf)
            act_next = jax.lax.ppermute(y, axis, perm)
            return (act_next, out_buf), None

        (_, out_buf), _ = jax.lax.scan(tick, (act0, out_buf0), jnp.arange(T))
        # only the last stage holds real outputs; broadcast them to every
        # stage so the replicated out_spec is consistent
        out_buf = jnp.where(stage == S - 1, out_buf, jnp.zeros_like(out_buf))
        return jax.lax.psum(out_buf, axis)

    n_param_dims = jax.tree_util.tree_map(lambda a: P(axis, *([None] * (a.ndim - 1))),
                                          stacked_params)
    mapped = jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(n_param_dims, P()),
        out_specs=P())
    return mapped(stacked_params, micro_inputs)
