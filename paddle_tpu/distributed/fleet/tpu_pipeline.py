"""Compiled pipeline parallelism over the ``pp`` mesh axis.

The truly-pipelined schedule (SURVEY.md §7 hard-part #1): for a UNIFORM stack
of blocks (the transformer case), per-stage parameters are stacked along a
leading axis sharded over ``pp``; one ``shard_map`` program runs the GPipe
schedule — a ``lax.scan`` over M + S - 1 ticks where every stage computes a
different microbatch each tick and activations hop stages with
``lax.ppermute``. XLA overlaps the ppermute with the next tick's compute
(async collective permute on ICI), which is exactly what the reference's
p2p_communication + 1F1B scheduling achieves with NCCL streams. Backward is
jax AD through the scan; ``jax.checkpoint`` on the stage body gives 1F1B's
activation-memory profile (only per-tick boundaries are stored).

Use through ``pipelined_forward`` (functional) or wire stacked params from a
PipelineLayer of identical LayerDescs.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ... import observability as _obs

__all__ = ["pipelined_forward", "stack_stage_params", "PipelinedStack",
           "HeteroPipelinedStack", "find_uniform_run",
           "NonUniformStackError"]


class NonUniformStackError(ValueError):
    """PipelineLayer has no block run stackable over the pp axis — callers
    fall back to the grad-accumulation path."""


def stack_stage_params(per_stage_params, mesh: Mesh, axis: str = "pp"):
    """Stack a list of S per-stage param pytrees along a new leading axis and
    shard it over ``axis``."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                     *per_stage_params)

    def place(a):
        spec = P(axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked)


def pipelined_forward(stage_fn: Callable, stacked_params, micro_inputs,
                      mesh: Mesh, axis: str = "pp", remat: bool = True,
                      batch_axis: Optional[str] = None, v_chunks: int = 1):
    """Run the GPipe schedule (or its interleaved/VPP variant).

    stage_fn(stage_params, x) -> y       one stage's computation
    stacked_params: pytree, leaves (S, ...) sharded over ``axis``
                    (``v_chunks > 1``: leaves (S, V, ...); stage_fn then
                    receives ONE chunk's params)
    micro_inputs:   (M, B_mb, ...) microbatched input (replicated, or with
                    the per-microbatch batch dim sharded over ``batch_axis``
                    for dp x pp hybrids — pass batch_axis="dp")
    returns         (M, B_mb, ...) outputs of the last stage

    ``v_chunks`` = upstream's virtual pipeline degree (interleaved 1F1B,
    python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py):
    device d holds model chunks {d, d+S, ...}; every tick it runs its V
    chunks and every chunk output hops one device, T = M + S*V - 1 ticks.
    Measured caveat (benchmarks/RESULTS.md "VPP refutation"): in the
    compiled SPMD scan this is ~1.9x SLOWER than GPipe-scan at V=2 — VPP's
    win exists only where the bubble is idle time a runtime can fill, and
    a compiled scan has no idle. The option exists for schedule parity and
    for re-measurement on future hardware/runtimes."""
    S = int(mesh.shape[axis])
    M = micro_inputs.shape[0]
    V = max(int(v_chunks), 1)
    T = M + S * V - 1
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    # Manual-axis policy: with only pp (+ dp batch) on the mesh, both are
    # manual (the classic layout). When the mesh ALSO carries tensor
    # parallelism (dp x mp x pp), only pp goes manual — dp and mp both ride
    # AUTO sharding propagation inside the body, because the XLA
    # partitioners reject the mixed manual set (shardy: "Manual sub-axis
    # isn't supported"; GSPMD: manual/auto dynamic-slice mismatch).
    extra_axes = {a for a in mesh.axis_names
                  if a != axis and a != batch_axis and int(mesh.shape[a]) > 1}
    if extra_axes:
        manual_axes = {axis}
        micro_spec = P(None)  # pp-replicated; batch/mp shardings flow auto
    else:
        manual_axes = {axis} | ({batch_axis} if batch_axis else set())
        micro_spec = P(None, batch_axis) if batch_axis else P()
    vary_axes = tuple(manual_axes)

    def local_fn(params_local, micro):
        # params_local leaves: (1, ...) — this stage's slice
        p_mine = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def vary(x):
            # fresh buffers must carry the same varying-axes set as the
            # activations written into them (pp hop + dp-sharded batch);
            # pcast rejects axes that are already varying, so add one by one
            for ax in vary_axes:
                try:
                    x = jax.lax.pcast(x, ax, to="varying")
                except ValueError:
                    pass  # already varying over ax
            return x

        out_buf0 = vary(jnp.zeros((M,) + micro.shape[1:], micro.dtype))

        if V == 1:
            act0 = vary(jnp.zeros_like(micro[0]))

            def tick(carry, t):
                act_in, out_buf = carry
                # stage 0 ingests microbatch t; later stages use the hop
                mb_idx = jnp.clip(t, 0, M - 1)
                x = jnp.where(stage == 0, micro[mb_idx], act_in)
                y = body(p_mine, x)
                # last stage records microbatch (t - S + 1) when valid
                rec = t - (S - 1)
                valid = jnp.logical_and(stage == S - 1,
                                        jnp.logical_and(rec >= 0, rec < M))
                out_buf = jax.lax.cond(
                    valid,
                    lambda ob: jax.lax.dynamic_update_index_in_dim(
                        ob, y, jnp.clip(rec, 0, M - 1), axis=0),
                    lambda ob: ob, out_buf)
                act_next = jax.lax.ppermute(y, axis, perm)
                return (act_next, out_buf), None

            (_, out_buf), _ = jax.lax.scan(tick, (act0, out_buf0),
                                           jnp.arange(T))
        else:
            # interleaved: this device's V chunks each advance one hop per
            # tick. acts[c] = activation entering chunk c here this tick.
            acts0 = [vary(jnp.zeros_like(micro[0])) for _ in range(V)]

            def tick(carry, t):
                acts, out_buf = carry
                ys = []
                for c in range(V):
                    x_in = acts[c]
                    if c == 0:
                        mb_idx = jnp.clip(t, 0, M - 1)
                        x_in = jnp.where(stage == 0, micro[mb_idx], x_in)
                    ys.append(body(
                        jax.tree_util.tree_map(lambda a, c=c: a[c], p_mine),
                        x_in))
                rec = t - (S * V - 1)
                valid = jnp.logical_and(stage == S - 1,
                                        jnp.logical_and(rec >= 0, rec < M))
                out_buf = jax.lax.cond(
                    valid,
                    lambda ob: jax.lax.dynamic_update_index_in_dim(
                        ob, ys[-1], jnp.clip(rec, 0, M - 1), axis=0),
                    lambda ob: ob, out_buf)
                hopped = [jax.lax.ppermute(y, axis, perm) for y in ys]
                # stage > 0, chunk c: continue chunk c from the previous
                # stage; stage 0, chunk c: start chunk c on what chunk c-1
                # finished at the LAST stage (the cyclic hop delivers it)
                new_acts = []
                for c in range(V):
                    if c == 0:
                        new_acts.append(hopped[0])  # stage 0 slot is
                        # overwritten by the microbatch at consumption
                    else:
                        new_acts.append(jnp.where(stage == 0,
                                                  hopped[c - 1], hopped[c]))
                return (new_acts, out_buf), None

            (_, out_buf), _ = jax.lax.scan(tick, (acts0, out_buf0),
                                           jnp.arange(T))
        # only the last stage holds real outputs; broadcast them to every
        # stage so the replicated out_spec is consistent
        out_buf = jnp.where(stage == S - 1, out_buf, jnp.zeros_like(out_buf))
        return jax.lax.psum(out_buf, axis)

    n_param_dims = jax.tree_util.tree_map(lambda a: P(axis, *([None] * (a.ndim - 1))),
                                          stacked_params)
    mapped = jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(n_param_dims, micro_spec),
        out_specs=micro_spec,
        axis_names=manual_axes)
    return mapped(stacked_params, micro_inputs)


# ---------------------------------------------------------------------------
# Fleet wiring: PipelineLayer -> stacked-stage engine
# ---------------------------------------------------------------------------

def _entry_key(layer):
    """Structural identity of a block: class + param tree (names/shapes/
    dtypes). Stages can be stacked iff their blocks agree on this key."""
    sd = layer.state_dict()
    return (type(layer).__name__,
            tuple((k, tuple(v._data.shape), str(v._data.dtype))
                  for k, v in sorted(sd.items())))


def _has_persistable_buffers(layer) -> bool:
    """True if the block carries persistable buffers (state_dict entries that
    are not parameters — e.g. BatchNorm running stats). Such blocks cannot be
    stacked: the engine would hand the buffers to the optimizer as weights,
    and in-forward buffer updates (running-stat EMAs) would be silently
    dropped by the pure stage function. They take the grad-accumulation
    fallback instead."""
    param_ids = {id(p) for p in layer.parameters()}
    return any(id(v) not in param_ids for v in layer.state_dict().values())


def _stackable_keys(entries):
    from ...nn.layer import Layer as _Layer

    keys = []
    for layer, ffunc in entries:
        if ffunc is not None or not isinstance(layer, _Layer) \
                or not layer.state_dict() or _has_persistable_buffers(layer):
            keys.append(None)  # boundary: can't be stacked
        else:
            keys.append(_entry_key(layer))
    return keys


def find_uniform_run(entries, num_stages):
    """Find the best contiguous run stackable over ``num_stages`` stages.

    A run of length S*q is stackable when its structural keys are PERIODIC
    with period q: entry (s*q + t) matches entry t for every stage s and
    slot t. q == 1 is the classic uniform-transformer case; q > 1 covers
    heterogeneous repeating stacks (BERT-shaped alternating attention/MLP
    entries, conv/attention interleaves) — the stage body simply runs its
    q slots in order, each slot with its own (S, ...) stacked parameters.

    Returns (start, n_used) with n_used = S*q*ceil-free (largest multiple
    of num_stages*q that fits), or None when nothing is stackable.
    """
    S = int(num_stages)
    keys = _stackable_keys(entries)
    n = len(keys)
    best = None  # (n_used, -q, start)
    # maximal boundary-free segments
    seg_start = 0
    while seg_start < n:
        if keys[seg_start] is None:
            seg_start += 1
            continue
        seg_end = seg_start
        while seg_end < n and keys[seg_end] is not None:
            seg_end += 1
        seg_len = seg_end - seg_start
        max_q = min(seg_len // S, 32)  # periods past 32 slots are implausible
        for q in range(1, max_q + 1):
            period = q
            # slide a window of length S*q*r — take the longest periodic
            # prefix at each offset; a simple O(len^2) scan is fine at
            # model-definition sizes
            for off in range(seg_start, seg_end - S * period + 1):
                length = 0
                while off + length < seg_end and \
                        keys[off + length] == keys[off + length % period]:
                    length += 1
                repeats = length // period
                usable_rep = (repeats // S) * S
                if usable_rep >= S:
                    n_used = usable_rep * period
                    cand = (n_used, -period, -off)
                    if best is None or cand > best:
                        best = (n_used, -period, -off)
        seg_start = seg_end
    if best is None:
        return None
    n_used, neg_q, neg_off = best
    return -neg_off, n_used


def _record_schedule_metrics(engine: str, S: int, M: int, V: int) -> None:
    """Per-step schedule telemetry. The schedule is compiled SPMD, so true
    per-stage wall time is not host-observable; what IS exact from the
    schedule structure is the bubble: of T = M + S*V - 1 scan ticks each
    stage computes useful microbatches for M, so the idle fraction is
    (S*V - 1) / T — the GPipe bubble. ``pipeline.step_seconds`` (observed
    around the dispatch at the call sites) covers the whole-step host time."""
    if not _obs.enabled():
        return
    T = M + S * V - 1
    _obs.inc("pipeline.steps_total", engine=engine)
    _obs.set_gauge("pipeline.stages", S)
    _obs.set_gauge("pipeline.micro_batches", M)
    _obs.set_gauge("pipeline.bubble_fraction", (S * V - 1) / T)


def _refine_run_bounds(entries, keys, lo, hi, num_stages, seg_method):
    """Refine a stackable run's edges to [lo, hi) for the hetero engine.

    ``seg_method="layer:Name"`` (upstream parity: stages split at the named
    block class) bounds the run to [first..last] Name block — but ONLY when
    at least ``num_stages`` named blocks exist. With fewer, upstream's
    placement contract cannot be honored; we WARN + count
    (``pipeline.seg_method_fallbacks_total``) and fall back to the
    param-balanced heuristic instead of silently diverging (ADVICE r5).
    Note the cuts inside the bounded run are still param-balanced, not
    aligned to Name blocks — see MIGRATING.md.

    The default heuristic trims edge blocks whose structural key is UNIQUE
    in the run while their inward neighbor's key repeats — the
    embedding/head shape of real models.
    """
    S = int(num_stages)
    if seg_method.startswith("layer:"):
        name = seg_method.split(":", 1)[1]
        idxs = [i for i in range(lo, hi)
                if type(entries[i][0]).__name__ == name]
        if len(idxs) >= S:
            return idxs[0], idxs[-1] + 1
        _obs.inc("pipeline.seg_method_fallbacks_total")
        warnings.warn(
            f"hetero pipeline: seg_method={seg_method!r} found only "
            f"{len(idxs)} {name!r} block(s) in the stackable run but "
            f"{S} pipeline stages need at least one each; falling back "
            "to param-balanced stage cuts (upstream would split at the "
            "named blocks)")
        # fall through to the heuristic
    from collections import Counter
    count = Counter(keys[lo:hi])
    while hi - lo > S and count[keys[lo]] == 1 and count[keys[lo + 1]] > 1:
        lo += 1
    while hi - lo > S and count[keys[hi - 1]] == 1 \
            and count[keys[hi - 2]] > 1:
        hi -= 1
    return lo, hi


class PipelinedStack:
    """Executes a PipelineLayer with REAL stage placement on the pp mesh
    axis (upstream parity: meta_parallel PipelineParallel + p2p_communication
    + 1F1B; SURVEY §7 hard-part 1).

    The maximal uniform run of blocks is stacked leaf-wise into (S, ...)
    parameters sharded over ``pp`` — each device stores only its stage's
    block weights. The forward is ONE program: pre-run layers (embedding
    side) execute on the full batch, the stacked run executes the GPipe
    ppermute schedule over microbatches, post-run layers (norm/head side)
    close the batch out. Schedule choice: GPipe-with-remat rather than 1F1B
    — under XLA both keep only per-tick boundary activations live (the scan
    carries one activation per stage; remat recomputes block internals in
    backward), which is the same O(S + M/S) activation profile 1F1B buys in
    the reference's hand-scheduled runtime, and XLA overlaps the ppermute
    hop with the next tick's compute like NCCL-stream overlap. Shared
    embeddings (SharedLayerDesc) need no explicit grad allreduce: the tied
    module runs replicated in pre AND post, so both uses hit the same
    parameter and the tape sums their gradients.

    Only parameters are stacked: blocks carrying persistable buffers
    (BatchNorm-style running stats) are never selected for stacking — they
    fall to the grad-accumulation path, where buffer updates apply normally.
    Non-persistable buffers (derived caches such as rotary tables) are read
    from the template block and therefore must be stage-invariant, which
    holds for identically-constructed blocks.
    """

    def __init__(self, pipeline_layer, mesh: Mesh, axis: str = "pp",
                 micro_batches: int = 1, remat: bool = True,
                 v_chunks: int = 1):
        from ...core.tensor import Parameter, Tensor
        from ...nn.layer import Layer as _Layer
        from ...nn.container import LayerList

        self._mesh = mesh
        self._axis = axis
        self._S = int(mesh.shape[axis])
        self._M = max(int(micro_batches), 1)
        self._V = max(int(v_chunks), 1)
        self._remat = remat
        self._loss_fn = pipeline_layer._loss_fn

        slots = self._S * self._V  # interleaved: V model chunks per stage
        entries = pipeline_layer._entries
        run = find_uniform_run(entries, slots)
        if run is None:
            raise NonUniformStackError(
                "PipelineLayer has no stage-periodic block run stackable "
                f"over {slots} stage-chunks (and none of its repeating "
                "segments is free of persistable buffers); the "
                "grad-accumulation fallback applies")
        start, n_used = run
        self._k = n_used // slots  # blocks per stage-chunk

        self._pre = entries[:start]
        self._post = entries[start + n_used:]
        blocks = [layer for layer, _ in entries[start:start + n_used]]
        self._template = blocks[:self._k]  # slot 0's blocks drive the trace

        # stack per-leaf over stages (and chunks when interleaved):
        # stacked[j][name] = (S, ...) or (S, V, ...); interleaved placement
        # is upstream VPP's: chunk c on stage s = global slot c*S + s
        self._leaf_names: List[List[str]] = []
        self._stacked: List[Dict[str, Any]] = []
        for j in range(self._k):
            names = sorted(self._template[j].state_dict().keys())
            self._leaf_names.append(names)
            leaves = {}
            for name in names:
                def slot_leaf(slot):
                    return blocks[slot * self._k + j].state_dict()[name]._data
                if self._V == 1:
                    arr = jnp.stack([slot_leaf(s) for s in range(self._S)], 0)
                else:
                    arr = jnp.stack(
                        [jnp.stack([slot_leaf(c * self._S + s)
                                    for c in range(self._V)], 0)
                         for s in range(self._S)], 0)
                spec = P(axis, *([None] * (arr.ndim - 1)))
                arr = jax.device_put(arr, NamedSharding(mesh, spec))
                param = Parameter(arr, name=f"pp_stack_{j}_{name}")
                leaves[name] = param
            self._stacked.append(leaves)

        # release non-template block originals: rebuild the PipelineLayer's
        # holders so stage>0 copies get garbage-collected (weakref registry
        # drops them, shrinking every to_static state signature)
        keep = [l for l, _ in self._pre if isinstance(l, _Layer)] \
            + list(self._template) \
            + [l for l, _ in self._post if isinstance(l, _Layer)]
        pipeline_layer.run_function = LayerList(keep)
        pipeline_layer._entries = list(self._pre) + \
            [(b, None) for b in self._template] + list(self._post)
        # direct use of the consumed PipelineLayer would run stale template
        # weights — its serial surface raises until accessed via the engine
        pipeline_layer._engine = self

    # -- parameters the optimizer owns --------------------------------------
    def parameters(self):
        from ...nn.layer import Layer as _Layer

        seen, out = set(), []
        for layer, _ in list(self._pre) + list(self._post):
            if isinstance(layer, _Layer):
                for p in layer.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        out.append(p)
        for leaves in self._stacked:
            for p in leaves.values():
                if id(p) not in seen:
                    seen.add(id(p))
                    out.append(p)
        return out

    def state_dict(self):
        from ...nn.layer import Layer as _Layer

        out = {}
        for i, (layer, _) in enumerate(list(self._pre) + list(self._post)):
            if isinstance(layer, _Layer):
                for k, v in layer.state_dict().items():
                    out[f"edge_{i}.{k}"] = v
        for j, leaves in enumerate(self._stacked):
            for name, p in leaves.items():
                out[f"pp_stack_{j}.{name}"] = p
        return out

    def set_state_dict(self, state_dict):
        """Load a dict produced by this engine's ``state_dict``."""
        own = self.state_dict()
        missing = [k for k in own if k not in state_dict]
        if missing:
            raise KeyError(f"pipelined state_dict missing keys: {missing}")
        for k, p in own.items():
            v = state_dict[k]
            arr = v._data if hasattr(v, "_data") else jnp.asarray(v)
            if tuple(arr.shape) != tuple(p._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: checkpoint {tuple(arr.shape)} "
                    f"vs parameter {tuple(p._data.shape)}")
            p._set_data(jax.device_put(arr.astype(p._data.dtype),
                                       p._data.sharding))

    # -- execution ----------------------------------------------------------
    def _run_edge(self, entries, x):
        from ...nn.layer import Layer as _Layer

        for layer, ffunc in entries:
            if ffunc is not None:
                x = ffunc(layer, x)
            else:
                x = layer(x)
        return x

    def __call__(self, x, micro_batches: Optional[int] = None):
        from ...core.tensor import Tensor, apply
        from ...core.tracing import no_grad

        x = self._run_edge(self._pre, x)

        M = self._M if micro_batches is None else max(int(micro_batches), 1)
        mesh, axis, S, k = self._mesh, self._axis, self._S, self._k
        batch_axis = ("dp" if "dp" in mesh.axis_names
                      and int(mesh.shape["dp"]) > 1 else None)
        template = self._template
        leaf_names = self._leaf_names
        remat = self._remat
        flat_params = [self._stacked[j][n]
                       for j in range(k) for n in leaf_names[j]]

        def fn(*arrays):
            stacked_arrays = arrays[:-1]
            xa = arrays[-1]
            B = xa.shape[0]
            assert B % M == 0, (
                f"batch {B} not divisible by accumulate_steps {M}")
            micro = xa.reshape((M, B // M) + xa.shape[1:])

            # rebuild the per-block param pytrees from the flat arg list
            trees, pos = [], 0
            for j in range(k):
                names = leaf_names[j]
                trees.append({n: stacked_arrays[pos + i]
                              for i, n in enumerate(names)})
                pos += len(names)

            def stage_fn(stage_params, h):
                # bind this stage's slices into the template blocks and run
                # them; inner tape recording is suppressed (gradients flow
                # through the OUTER vjp of this pure fn)
                with no_grad():
                    for j, block in enumerate(template):
                        sd = block.state_dict()
                        saved = {n: sd[n]._data for n in leaf_names[j]}
                        for n in leaf_names[j]:
                            sd[n]._data = stage_params[j][n]
                        try:
                            h = block(Tensor(h))._data
                        finally:
                            for n in leaf_names[j]:
                                sd[n]._data = saved[n]
                return h

            out = pipelined_forward(stage_fn, trees, micro, mesh, axis,
                                    remat=remat, batch_axis=batch_axis,
                                    v_chunks=self._V)
            return out.reshape((B,) + out.shape[2:])

        _record_schedule_metrics("uniform", S, M, self._V)
        with _obs.scoped_timer("pipeline.step_seconds"):
            out = apply("pipelined_stack", fn, *flat_params, x,
                        differentiable=True, amp=False)
        return self._run_edge(self._post, out)


class HeteroPipelinedStack:
    """REAL stage placement for NON-uniform stacks (round 5; closes the
    VERDICT r4 grad-accum-fallback gap; upstream parity: meta_parallel
    PipelineParallel places arbitrary LayerDesc partitions per stage).

    The uniform engine requires a stage-periodic block run it can stack
    leaf-wise. Here stages may have DIFFERENT block structures; SPMD still
    requires one program, so:

    * the longest boundary-free run of param-carrying blocks is split into
      S contiguous stages balanced by parameter count;
    * each stage's parameters are flattened per dtype, padded to the max
      stage length, and stacked into one (S, Lmax) buffer per dtype
      sharded over ``pp`` — each device stores only its own stage's
      weights (plus padding, the price of SPMD uniformity);
    * the stage body is ``lax.switch(axis_index(pp), branches)``: branch s
      statically unflattens its slice layout and runs stage s's actual
      blocks. Activations still hop with ppermute in the same GPipe scan
      (``pipelined_forward``), so the schedule, remat, and overlap
      behavior are shared with the uniform engine.

    Requirements (validated at first call): every stage's input and output
    activation must have the SAME shape/dtype (the hop buffer is one
    uniform array). Blocks with persistable buffers (BatchNorm running
    stats) are excluded from the run, as in the uniform engine.

    Divergence note: the optimizer sees one fused Parameter per dtype per
    stage-stack, so per-leaf weight-decay masking does not apply inside
    the pipelined run (matching the uniform engine's stacked-leaf
    granularity trade-off, one step coarser).
    """

    def __init__(self, pipeline_layer, mesh: Mesh, axis: str = "pp",
                 micro_batches: int = 1, remat: bool = True):
        from ...core.tensor import Parameter
        from ...nn.layer import Layer as _Layer
        from ...nn.container import LayerList

        self._mesh = mesh
        self._axis = axis
        self._S = int(mesh.shape[axis])
        self._M = max(int(micro_batches), 1)
        self._remat = remat
        self._loss_fn = pipeline_layer._loss_fn

        entries = pipeline_layer._entries
        keys = _stackable_keys(entries)
        # longest boundary-free run of param blocks
        best = (0, 0)  # (len, start)
        i = 0
        while i < len(keys):
            if keys[i] is None:
                i += 1
                continue
            j = i
            while j < len(keys) and keys[j] is not None:
                j += 1
            if j - i > best[0]:
                best = (j - i, i)
            i = j
        n_run, start = best
        if n_run < self._S:
            raise NonUniformStackError(
                f"PipelineLayer has only {n_run} contiguous stackable "
                f"blocks; {self._S} pipeline stages need at least one "
                "block each (persistable-buffer blocks are excluded)")
        # refine the run's edges: the hop buffer needs ONE activation shape
        # across all stage boundaries, and shape-CHANGING layers live at
        # the model's edges (embedding in, head out). Two mechanisms:
        # * seg_method="layer:Name" (upstream parity: stages split at the
        #   named block class) bounds the run to [first..last] Name block;
        # * default heuristic: trim edge blocks whose structural key is
        #   UNIQUE in the run while their inward neighbor's key repeats —
        #   the embedding/head shape of real models. Validation at first
        #   call still backstops both with an actionable error.
        seg = getattr(pipeline_layer, "_seg_method", "uniform") or "uniform"
        lo, hi = _refine_run_bounds(entries, keys, start, start + n_run,
                                    self._S, seg)
        start, n_run = lo, hi - lo
        self._pre = entries[:start]
        self._post = entries[start + n_run:]
        blocks = [layer for layer, _ in entries[start:start + n_run]]

        # contiguous split into S NON-EMPTY groups, balanced by param count:
        # cut at the running-total thresholds, but force a cut whenever the
        # remaining blocks are exactly the remaining stages (so a skewed
        # size distribution — e.g. one giant last block — can never leave a
        # stage empty)
        sizes = [sum(int(np.prod(p._data.shape)) for p in b.parameters())
                 for b in blocks]
        total = sum(sizes)
        bounds = [0]
        acc = 0
        for idx, sz in enumerate(sizes):
            acc += sz
            cuts_left = self._S - len(bounds)
            blocks_left = n_run - (idx + 1)
            if cuts_left > 0 and blocks_left >= cuts_left and \
                    (acc >= total * len(bounds) / self._S
                     or blocks_left == cuts_left):
                bounds.append(idx + 1)
        bounds.append(n_run)
        assert len(bounds) == self._S + 1 and \
            all(b > a for a, b in zip(bounds, bounds[1:])), bounds
        self._stage_blocks = [blocks[bounds[s]:bounds[s + 1]]
                              for s in range(self._S)]

        # pack: per stage, per dtype, a flat concat; pad to max; stack (S, L)
        layouts: List[List[tuple]] = []  # per stage: (blk, name, shape, off, dt)
        per_dtype_rows: Dict[str, List[np.ndarray]] = {}
        self._dtypes: List[str] = []
        stage_rows: List[Dict[str, Any]] = []
        for s in range(self._S):
            offs: Dict[str, int] = {}
            rows: Dict[str, List[Any]] = {}
            layout = []
            for bi, b in enumerate(self._stage_blocks[s]):
                sd = b.state_dict()
                for name in sorted(sd.keys()):
                    arr = sd[name]._data
                    dt = str(arr.dtype)
                    off = offs.get(dt, 0)
                    layout.append((bi, name, tuple(arr.shape), off, dt))
                    offs[dt] = off + int(np.prod(arr.shape))
                    rows.setdefault(dt, []).append(jnp.ravel(arr))
            layouts.append(layout)
            stage_rows.append({dt: jnp.concatenate(v) if len(v) > 1 else v[0]
                               for dt, v in rows.items()})
        self._layouts = layouts
        dtypes = sorted({dt for r in stage_rows for dt in r})
        self._dtypes = dtypes
        self._buffers: Dict[str, Any] = {}
        for dt in dtypes:
            lmax = max(int(r[dt].shape[0]) if dt in r else 0
                       for r in stage_rows)
            stackrows = []
            for s in range(self._S):
                row = stage_rows[s].get(dt)
                if row is None:
                    row = jnp.zeros((lmax,), dtype=dt)
                elif int(row.shape[0]) < lmax:
                    row = jnp.pad(row, (0, lmax - int(row.shape[0])))
                stackrows.append(row)
            arr = jnp.stack(stackrows, 0)
            arr = jax.device_put(arr, NamedSharding(mesh, P(axis, None)))
            self._buffers[dt] = Parameter(arr, name=f"pp_hetero_{dt}")

        # placement telemetry inputs, kept on the engine: the gauges are
        # (re)recorded on every __call__ so metrics enabled AFTER engine
        # construction (the StepTelemetry flow) still see them
        self._stage_param_sizes = [sum(sizes[bounds[s]:bounds[s + 1]])
                                   for s in range(self._S)]
        real = sum(int(r[dt].shape[0]) for r in stage_rows for dt in r)
        padded = sum(int(np.prod(self._buffers[dt]._data.shape))
                     for dt in dtypes)
        self._padding_fraction = 0.0 if padded == 0 else 1.0 - real / padded

        self._pipeline_layer = pipeline_layer
        self._orig_entries = list(entries)
        self._orig_run_function = pipeline_layer.run_function
        # the originals are TRACE TEMPLATES from here on — their values
        # live in the fused buffers; shrink every packed leaf to a scalar
        # placeholder so the engine doesn't keep a second full copy of the
        # model's weights alive (branches swap real slices in before any
        # compute and restore the placeholder after)
        for s in range(self._S):
            for bi, name, shape, off, dt in self._layouts[s]:
                sd = self._stage_blocks[s][bi].state_dict()
                sd[name]._set_data(jnp.zeros((), dtype=dt))

        # release per-stage originals from the layer tree (stage blocks
        # stay referenced by the engine for tracing/layout)
        keep = [l for l, _ in self._pre if isinstance(l, _Layer)] + \
            [l for l, _ in self._post if isinstance(l, _Layer)]
        pipeline_layer.run_function = LayerList(keep)
        pipeline_layer._engine = self

    def dismantle(self) -> None:
        """Undo engine construction: unpack every stage's weights from the
        fused buffers back into the original block parameters and restore
        the PipelineLayer's entry list — the graceful path back to the
        grad-accumulation fallback when first-call validation rejects the
        stack. NOTE: an optimizer built from this engine's parameters()
        (the fused buffers) must be rebuilt after dismantling."""
        for s in range(self._S):
            row = {dt: self._buffers[dt]._data[s] for dt in self._dtypes}
            for bi, name, shape, off, dt in self._layouts[s]:
                sd = self._stage_blocks[s][bi].state_dict()
                n = int(np.prod(shape))
                sd[name]._set_data(
                    jax.lax.dynamic_slice_in_dim(row[dt], off, n, 0)
                    .reshape(shape))
        self._pipeline_layer._entries = self._orig_entries
        self._pipeline_layer.run_function = self._orig_run_function
        self._pipeline_layer._engine = None

    # -- parameters the optimizer owns --------------------------------------
    def parameters(self):
        from ...nn.layer import Layer as _Layer

        seen, out = set(), []
        for layer, _ in list(self._pre) + list(self._post):
            if isinstance(layer, _Layer):
                for p in layer.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        out.append(p)
        for dt in self._dtypes:
            out.append(self._buffers[dt])
        return out

    def state_dict(self):
        from ...nn.layer import Layer as _Layer

        out = {}
        for i, (layer, _) in enumerate(list(self._pre) + list(self._post)):
            if isinstance(layer, _Layer):
                for k, v in layer.state_dict().items():
                    out[f"edge_{i}.{k}"] = v
        for dt in self._dtypes:
            out[f"pp_hetero.{dt}"] = self._buffers[dt]
        return out

    def set_state_dict(self, state_dict):
        own = self.state_dict()
        missing = [k for k in own if k not in state_dict]
        if missing:
            raise KeyError(f"hetero pipelined state_dict missing: {missing}")
        for k, p in own.items():
            v = state_dict[k]
            arr = v._data if hasattr(v, "_data") else jnp.asarray(v)
            if tuple(arr.shape) != tuple(p._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: checkpoint {tuple(arr.shape)} "
                    f"vs parameter {tuple(p._data.shape)}")
            p._set_data(jax.device_put(arr.astype(p._data.dtype),
                                       p._data.sharding))

    # -- execution ----------------------------------------------------------
    def _run_edge(self, entries, x):
        for layer, ffunc in entries:
            x = ffunc(layer, x) if ffunc is not None else layer(x)
        return x

    def _branch(self, s):
        """Stage-s body on raw arrays: statically unflatten this stage's
        layout from the per-dtype rows and run its actual blocks."""
        from ...core.tensor import Tensor
        from ...core.tracing import no_grad

        layout = self._layouts[s]
        stage_blocks = self._stage_blocks[s]

        def run(rows, h):
            with no_grad():
                saved = []
                for bi, name, shape, off, dt in layout:
                    sd = stage_blocks[bi].state_dict()
                    saved.append((sd[name], sd[name]._data))
                    n = int(np.prod(shape))
                    sd[name]._data = jax.lax.dynamic_slice_in_dim(
                        rows[dt], off, n, 0).reshape(shape)
                try:
                    for b in stage_blocks:
                        h = b(Tensor(h))._data
                finally:
                    for t, old in saved:
                        t._data = old
            return h

        return run

    def _validate_boundaries(self, x):
        """First-call check: every stage must map the hop-buffer aval to
        itself (one uniform ppermute payload is the SPMD-scan contract).
        Raises NonUniformStackError with the actionable fix otherwise."""
        if getattr(self, "_validated", False):
            return
        aval = jax.ShapeDtypeStruct(tuple(x._data.shape), x._data.dtype)
        rows = {dt: jax.ShapeDtypeStruct(
            tuple(self._buffers[dt]._data.shape[1:]),
            self._buffers[dt]._data.dtype) for dt in self._dtypes}
        for s in range(self._S):
            out = jax.eval_shape(self._branch(s), rows, aval)
            if tuple(out.shape) != tuple(aval.shape) or \
                    out.dtype != aval.dtype:
                raise NonUniformStackError(
                    f"hetero pipeline stage {s} maps activation "
                    f"{tuple(aval.shape)}/{aval.dtype} -> "
                    f"{tuple(out.shape)}/{out.dtype}; the compiled SPMD "
                    "schedule needs ONE uniform hop-buffer shape across "
                    "all stage boundaries. Either regroup the model so "
                    "shape-changing layers sit in the pre/post edges, or "
                    "set pipeline_configs={'hetero_pipeline': False} to "
                    "use the grad-accumulation fallback")
        self._validated = True

    def __call__(self, x, micro_batches: Optional[int] = None):
        from ...core.tensor import apply

        x = self._run_edge(self._pre, x)
        self._validate_boundaries(x)
        M = self._M if micro_batches is None else max(int(micro_batches), 1)
        mesh, axis, S = self._mesh, self._axis, self._S
        dtypes = self._dtypes
        batch_axis = ("dp" if "dp" in mesh.axis_names
                      and int(mesh.shape["dp"]) > 1 else None)
        branches = [self._branch(s) for s in range(S)]

        def fn(*arrays):
            rows_stacked = {dt: arrays[i] for i, dt in enumerate(dtypes)}
            xa = arrays[len(dtypes)]
            B = xa.shape[0]
            assert B % M == 0, (
                f"batch {B} not divisible by accumulate_steps {M}")
            micro = xa.reshape((M, B // M) + xa.shape[1:])

            def stage_fn(rows_local, h):
                stage = jax.lax.axis_index(axis)
                return jax.lax.switch(
                    stage, [lambda h, b=b: b(rows_local, h)
                            for b in branches], h)

            out = pipelined_forward(stage_fn, rows_stacked, micro, mesh,
                                    axis, remat=self._remat,
                                    batch_axis=batch_axis)
            return out.reshape((B,) + out.shape[2:])

        _record_schedule_metrics("hetero", S, M, 1)
        if _obs.enabled():
            # placement telemetry: balanced cuts are only as good as their
            # skew, and pad-to-max SPMD slots are pure memory waste
            for s, n in enumerate(self._stage_param_sizes):
                _obs.set_gauge("pipeline.stage_params", n, stage=s)
            _obs.set_gauge("pipeline.padding_fraction",
                           self._padding_fraction)
        flat = [self._buffers[dt] for dt in dtypes]
        with _obs.scoped_timer("pipeline.step_seconds"):
            out = apply("hetero_pipelined_stack", fn, *flat, x,
                        differentiable=True, amp=False)
        return self._run_edge(self._post, out)
