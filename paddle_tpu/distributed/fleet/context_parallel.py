"""Context / sequence parallelism across the ``sep`` mesh axis.

Parity surface (SURVEY.md §5 long-context items 2-3):
* Ulysses-style segment parallelism — PaddleNLP's ``sep_group`` alltoall
  that flips activations between sequence-sharded and head-sharded layouts
  around attention;
* ring attention — PaddleNLP ``ring_flash_attention.py``: K/V blocks rotate
  around the ring with online-softmax accumulation, so sequences longer than
  one device's memory train exactly.

TPU-native: Ulysses = two sharding constraints (XLA emits the all-to-alls);
ring attention = ``shard_map`` over the sep axis with ``lax.ppermute``
K/V rotation — collectives ride ICI and jax AD differentiates through the
ring (no hand-written backward).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor, apply
from ...nn.layer import Layer
from ..topology import get_hybrid_communicate_group

__all__ = ["ulysses_attention", "ring_flash_attention", "RingFlashAttention",
           "split_inputs_sequence_dim"]

_NEG_INF = -1e30


def _sep_mesh():
    hcg = get_hybrid_communicate_group()
    if hcg is None or hcg.get_sep_parallel_world_size() <= 1:
        return None, None
    return hcg.mesh, "sep"


def split_inputs_sequence_dim(x: Tensor, seq_dim: int = 1) -> Tensor:
    """Shard the sequence dim of (B, L, ...) over the sep axis (parity:
    PaddleNLP split_inputs_sequence_dim)."""
    mesh, axis = _sep_mesh()
    if mesh is None:
        return x
    spec = [None] * x._data.ndim
    spec[seq_dim] = axis
    return apply("sep_split", lambda a: jax.lax.with_sharding_constraint(
        a, NamedSharding(mesh, P(*spec))), x)


def ulysses_attention(q: Tensor, k: Tensor, v: Tensor, causal: bool = False,
                      training: bool = True) -> Tensor:
    """DeepSpeed-Ulysses pattern on (B, L, H, D) seq-sharded inputs: flip to
    head-sharded via alltoall, full-sequence attention per device on H/g
    heads, flip back."""
    mesh, axis = _sep_mesh()
    from ...ops.flash_attention import flash_attention
    if mesh is None:
        return flash_attention(q, k, v, causal=causal, training=training)

    head_spec = P(None, None, axis, None)
    seq_spec = P(None, axis, None, None)

    def constrain(t, spec):
        return apply("sep_constraint",
                     lambda a: jax.lax.with_sharding_constraint(
                         a, NamedSharding(mesh, spec)), t)

    q = constrain(q, head_spec)  # alltoall: seq-shard -> head-shard
    k = constrain(k, head_spec)
    v = constrain(v, head_spec)
    out = flash_attention(q, k, v, causal=causal, training=training)
    return constrain(out, seq_spec)  # alltoall back


def _ring_attention_global(q, k, v, mesh: Mesh, axis: str, causal: bool,
                           sm_scale: float):
    """q/k/v global (B, L, H, D), L sharded over ``axis``. Pure-jax ring with
    online softmax; AD-differentiable."""
    g = int(mesh.shape[axis])
    spec = P(None, axis, None, None)

    def local_fn(ql, kl, vl):
        # local (B, Lc, H, D) -> (B, H, Lc, D)
        qh = jnp.swapaxes(ql, 1, 2).astype(jnp.float32) * sm_scale
        my = jax.lax.axis_index(axis)
        b, h, lc, d = qh.shape

        # carries must be device-varying for the scan over ppermute steps
        def vary(x):
            return jax.lax.pcast(x, axis, to="varying")
        m0 = vary(jnp.full((b, h, lc, 1), _NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((b, h, lc, 1), jnp.float32))
        acc0 = vary(jnp.zeros((b, h, lc, d), jnp.float32))
        perm = [(i, (i + 1) % g) for i in range(g)]

        def step(carry, s):
            m, l, acc, kc, vc = carry
            src = (my - s) % g  # which rank's block we currently hold
            kh = jnp.swapaxes(kc, 1, 2).astype(jnp.float32)
            vh = jnp.swapaxes(vc, 1, 2).astype(jnp.float32)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)
            if causal:
                q_ids = my * lc + jax.lax.broadcasted_iota(
                    jnp.int32, (lc, lc), 0)
                k_ids = src * lc + jax.lax.broadcasted_iota(
                    jnp.int32, (lc, lc), 1)
                mask = q_ids[None, None] >= k_ids[None, None]
                logits = jnp.where(mask, logits, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
            # guard fully-masked rows (exp(-inf - -inf))
            m_safe = jnp.maximum(m_new, _NEG_INF / 2)
            p = jnp.exp(logits - m_safe)
            alpha = jnp.exp(jnp.maximum(m, _NEG_INF / 2) - m_safe)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = alpha * acc + jnp.einsum("bhqk,bhkd->bhqd", p, vh)
            kc2 = jax.lax.ppermute(kc, axis, perm)
            vc2 = jax.lax.ppermute(vc, axis, perm)
            return (m_new, l_new, acc_new, kc2, vc2), None

        (m, l, acc, _, _), _ = jax.lax.scan(
            step, (m0, l0, acc0, kl, vl), jnp.arange(g))
        out = acc / jnp.maximum(l, 1e-30)
        return jnp.swapaxes(out, 1, 2).astype(ql.dtype)

    mapped = jax.shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec)
    return mapped(q, k, v)


def ring_flash_attention(q: Tensor, k: Tensor, v: Tensor, causal: bool = True,
                         group=None, training: bool = True) -> Tensor:
    """PaddleNLP RingFlashAttention parity. Inputs (B, L, H, D) with L
    sharded (or shardable) over the sep axis."""
    mesh, axis = _sep_mesh()
    if group is not None:
        mesh, axis = group.mesh, group.axis_name
    from ...ops.flash_attention import flash_attention
    if mesh is None or int(mesh.shape[axis]) == 1:
        return flash_attention(q, k, v, causal=causal, training=training)
    d = q._data.shape[-1]
    sm_scale = 1.0 / math.sqrt(d)

    def f(qa, ka, va):
        spec = P(None, axis, None, None)
        qa = jax.lax.with_sharding_constraint(qa, NamedSharding(mesh, spec))
        ka = jax.lax.with_sharding_constraint(ka, NamedSharding(mesh, spec))
        va = jax.lax.with_sharding_constraint(va, NamedSharding(mesh, spec))
        return _ring_attention_global(qa, ka, va, mesh, axis, causal, sm_scale)

    return apply("ring_flash_attention", f, q, k, v)


class RingFlashAttention(Layer):
    def __init__(self, causal: bool = True, group=None):
        super().__init__()
        self.causal = causal
        self.group = group

    def forward(self, q, k, v):
        return ring_flash_attention(q, k, v, causal=self.causal,
                                    group=self.group, training=self.training)
