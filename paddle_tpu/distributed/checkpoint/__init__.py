"""Distributed checkpoint with reshard-on-load.

Parity surface: python/paddle/distributed/checkpoint/
(``save_state_dict``/``load_state_dict`` — per-rank shard files + metadata
with global shape/placements, resharding when the load topology differs).

TPU-native: arrays are handed to orbax AS SHARDED ``jax.Array``s — each
host serializes only its addressable shards (no full host gather, so a 7B
state never funnels through one host), ``async_save`` rides orbax's
AsyncCheckpointer (device-to-host copy happens synchronously, file IO in
the background), and load passes each destination tensor's CURRENT
sharding as a restore arg, so orbax reads exactly the shards the new
topology needs — reshard-on-load across different meshes (e.g. save on
(dp=2, mp=4), load on (dp=4, mp=2)) is exercised by
tests/test_distributed_checkpoint.py.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...core.tensor import Tensor, to_tensor

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict",
           "wait_async_saves"]


def _spec_of(t: Tensor):
    arr = t._data
    try:
        sh = arr.sharding
        if hasattr(sh, "spec"):
            return [list(p) if isinstance(p, tuple) else p for p in sh.spec]
    except Exception:
        pass  # tracer / committed-elsewhere array: no readable sharding spec
    return None


_ASYNC: List[Any] = []  # pending (ckptr | thread) handles


def _globalize_host_local(arrays: Dict[str, Any]) -> None:
    """Multi-process saves can only serialize GLOBAL arrays. Host-local
    entries (single-device scalars like step counters, or values created
    outside the mesh) are converted IN PLACE to globally-replicated arrays.
    Every process must hold the same value — that is checked with ONE
    pytree allgather over all such keys (not one collective per key), and
    the written value is rank 0's (deterministic: never whichever replica
    orbax picks as primary)."""
    if jax.process_count() == 1:
        return
    local = {k: np.asarray(a) for k, a in arrays.items()
             if a.is_fully_addressable}
    if not local:
        return
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    gathered = multihost_utils.process_allgather(local)
    mesh = Mesh(np.array(jax.devices()), ("_ckpt",))
    repl = NamedSharding(mesh, P())
    for k, host in local.items():
        g = np.asarray(gathered[k])
        exact = not np.issubdtype(host.dtype, np.inexact)
        same = np.array_equal(g, np.broadcast_to(g[0:1], g.shape)) if exact \
            else np.allclose(g, g[0:1], equal_nan=True)
        if not same:
            raise ValueError(
                f"checkpoint key {k!r} is a host-local array whose value "
                "differs across processes; make it a global (mesh-placed) "
                "array or reconcile it before save_state_dict")
        canonical = g[0]  # rank 0's value: deterministic content
        arrays[k] = jax.make_array_from_callback(
            canonical.shape, repl,
            lambda idx, _c=canonical: _c[idx])


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False) -> None:
    os.makedirs(path, exist_ok=True)
    flat = _flatten("", state_dict)
    meta = {}
    arrays: Dict[str, Any] = {}
    for k, v in flat.items():
        if isinstance(v, Tensor):
            # raw (possibly sharded) jax.Array — orbax writes per-shard;
            # no np.asarray host gather here
            arrays[k] = v._data
            meta[k] = {"shape": list(v._data.shape),
                       "dtype": str(v._data.dtype),
                       "spec": _spec_of(v)}
        else:
            meta[k] = {"value": v}
    _globalize_host_local(arrays)
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f)

    try:
        import orbax.checkpoint as ocp
    except Exception:
        ocp = None

    if ocp is not None:
        if async_save:
            ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
            _ASYNC.append(ckptr)
        else:
            ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).save(
                os.path.join(path, "arrays"), arrays, force=True)
        return

    # fallback without orbax: single-file npz (full host gather — small
    # states only; orbax is the supported path)
    def _write():
        np.savez(os.path.join(path, "arrays.npz"),
                 **{k: np.asarray(a) for k, a in arrays.items()})

    if async_save:
        t = threading.Thread(target=_write, daemon=True)
        t.start()
        _ASYNC.append(t)
    else:
        _write()


def wait_async_saves() -> None:
    for h in _ASYNC:
        if hasattr(h, "wait_until_finished"):
            h.wait_until_finished()
            try:
                h.close()
            except Exception:
                pass  # double-close of a finished async handle is benign
        else:
            h.join()
    _ASYNC.clear()


def async_save_state_dict(state_dict, path, **kw):
    return save_state_dict(state_dict, path, async_save=True, **kw)


def _target_sharding(t: Tensor):
    """The destination's concrete sharding (NamedSharding for mesh-placed
    tensors, SingleDeviceSharding for plain ones) — orbax restores exactly
    the shards it needs for it; a checkpoint written by OTHER processes'
    devices can only be read by passing a concrete local sharding."""
    try:
        sh = t._data.sharding
        if isinstance(sh, jax.sharding.Sharding):
            return sh
    except Exception:
        pass  # tracer payload: sharding is unreadable, caller falls back
    return None


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False) -> None:
    """Load INTO ``state_dict``'s tensors (paddle semantics), resharding to
    each destination tensor's current placement."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    flat = {k: t for k, t in _flatten("", state_dict).items()
            if isinstance(t, Tensor)}
    for k in flat:
        if k not in meta or "value" in meta.get(k, {}):
            raise KeyError(f"checkpoint at {path} has no entry {k!r}")
        src_shape = meta[k]["shape"]
        if list(src_shape) != list(flat[k]._data.shape):
            raise ValueError(
                f"shape mismatch for {k}: checkpoint {src_shape} vs target "
                f"{tuple(flat[k]._data.shape)}")

    arrays = None
    arrays_dir = os.path.join(path, "arrays")
    if os.path.isdir(arrays_dir):
        import orbax.checkpoint as ocp
        # PARTIAL restore: only the target tree's keys are read (item template
        # + transforms={} makes orbax skip the rest) — a model-only load from
        # a checkpoint that also holds optimizer m/v never materializes the
        # optimizer state, and each restored key reads exactly the shards its
        # destination sharding needs (reshard-on-load)
        restore_args = {}
        item = {}
        for k, t in flat.items():
            sh = _target_sharding(t)
            if sh is not None:
                restore_args[k] = ocp.ArrayRestoreArgs(sharding=sh)
            else:
                restore_args[k] = ocp.RestoreArgs()
            try:
                item[k] = jax.ShapeDtypeStruct(
                    tuple(meta[k]["shape"]), np.dtype(meta[k]["dtype"]),
                    sharding=sh)
            except TypeError:
                item[k] = jax.ShapeDtypeStruct(
                    tuple(meta[k]["shape"]), np.dtype(meta[k]["dtype"]))
        arrays = ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).restore(
            arrays_dir, item=item, restore_args=restore_args, transforms={})
    else:
        npz = np.load(os.path.join(path, "arrays.npz"))
        arrays = {k: npz[k] for k in npz.files}

    for k, tgt in flat.items():
        src = arrays[k]
        if isinstance(src, jax.Array) and _target_sharding(tgt) is not None \
                and src.sharding == tgt._data.sharding:
            arr = src.astype(tgt._data.dtype) \
                if src.dtype != tgt._data.dtype else src
        else:
            host = np.asarray(src)
            try:
                arr = jax.device_put(host.astype(tgt._data.dtype),
                                     tgt._data.sharding)
            except Exception:
                arr = jax.numpy.asarray(host.astype(tgt._data.dtype))
        tgt._set_data(arr)


def _flatten(prefix: str, obj) -> Dict[str, Any]:
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(f"{prefix}.{k}" if prefix else str(k), v))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(_flatten(f"{prefix}.{i}", v))
    else:
        out[prefix] = obj
    return out
