"""Distributed checkpoint with reshard-on-load.

Parity surface: python/paddle/distributed/checkpoint/
(``save_state_dict``/``load_state_dict`` — per-rank shard files + metadata
with global shape/placements, resharding when the load topology differs).
TPU-native: arrays are saved via orbax (async-capable, multi-host-aware);
shardings are recorded as (axis spec) metadata, and on load the arrays are
``device_put`` onto the CURRENT mesh — reshard-on-load is free because XLA
relayouts to whatever the new topology needs.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...core.tensor import Tensor, to_tensor

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict"]


def _spec_of(t: Tensor):
    arr = t._data
    try:
        sh = arr.sharding
        if hasattr(sh, "spec"):
            return [list(p) if isinstance(p, tuple) else p for p in sh.spec]
    except Exception:
        pass
    return None


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False) -> None:
    os.makedirs(path, exist_ok=True)
    flat = _flatten("", state_dict)
    meta = {}
    arrays = {}
    for k, v in flat.items():
        if isinstance(v, Tensor):
            arrays[k] = np.asarray(v._data)
            meta[k] = {"shape": list(v._data.shape),
                       "dtype": str(v._data.dtype),
                       "spec": _spec_of(v)}
        else:
            meta[k] = {"value": v}

    def _write():
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.join(path, "arrays"), arrays, force=True)
        except Exception:
            np.savez(os.path.join(path, "arrays.npz"), **arrays)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)

    if async_save:
        t = threading.Thread(target=_write, daemon=True)
        t.start()
        _ASYNC_THREADS.append(t)
    else:
        _write()


_ASYNC_THREADS = []


def wait_async_saves() -> None:
    for t in _ASYNC_THREADS:
        t.join()
    _ASYNC_THREADS.clear()


def async_save_state_dict(state_dict, path, **kw):
    return save_state_dict(state_dict, path, async_save=True, **kw)


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False) -> None:
    """Load INTO ``state_dict``'s tensors (paddle semantics), resharding to
    each destination tensor's current placement."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    arrays = None
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        arrays = ckptr.restore(os.path.join(path, "arrays"))
    except Exception:
        npz = np.load(os.path.join(path, "arrays.npz"))
        arrays = {k: npz[k] for k in npz.files}
    flat = _flatten("", state_dict)
    for k, tgt in flat.items():
        if not isinstance(tgt, Tensor):
            continue
        if k not in arrays:
            raise KeyError(f"checkpoint at {path} has no entry {k!r}")
        src = np.asarray(arrays[k])
        if list(src.shape) != list(tgt._data.shape):
            raise ValueError(f"shape mismatch for {k}: checkpoint "
                             f"{src.shape} vs target {tuple(tgt._data.shape)}")
        # reshard-on-load: place with the destination's current sharding
        try:
            sharding = tgt._data.sharding
            arr = jax.device_put(src.astype(tgt._data.dtype), sharding)
        except Exception:
            arr = jax.numpy.asarray(src.astype(tgt._data.dtype))
        tgt._set_data(arr)


def _flatten(prefix: str, obj) -> Dict[str, Any]:
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(f"{prefix}.{k}" if prefix else str(k), v))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(_flatten(f"{prefix}.{i}", v))
    else:
        out[prefix] = obj
    return out
