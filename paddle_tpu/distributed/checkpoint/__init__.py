"""Distributed checkpoint with reshard-on-load and crash-safe commits.

Parity surface: python/paddle/distributed/checkpoint/
(``save_state_dict``/``load_state_dict`` — per-rank shard files + metadata
with global shape/placements, resharding when the load topology differs).

TPU-native: arrays are handed to orbax AS SHARDED ``jax.Array``s — each
host serializes only its addressable shards (no full host gather, so a 7B
state never funnels through one host), ``async_save`` rides orbax's
AsyncCheckpointer (device-to-host copy happens synchronously, file IO in
the background), and load passes each destination tensor's CURRENT
sharding as a restore arg, so orbax reads exactly the shards the new
topology needs — reshard-on-load across different meshes (e.g. save on
(dp=2, mp=4), load on (dp=4, mp=2)) is exercised by
tests/test_distributed_checkpoint.py.

Crash safety (paddle_tpu.resilience integration):

* every bookkeeping file is written atomically — unique tmp name, fsync,
  ``os.replace``, directory fsync — so a kill mid-write never leaves a
  half-written ``metadata.json`` masquerading as a real one;
* a save COMMITS by writing ``manifest.json`` LAST: per-array CRC32
  checksums (``null`` for arrays not fully addressable by this process —
  multi-host shards can't be checksummed without the gather this module
  exists to avoid) plus shapes/dtypes. A directory without a committed
  manifest is an interrupted save, never a loadable checkpoint;
* after the manifest commits, ``latest`` / ``latest.prev`` pointer files
  in the checkpoint's PARENT directory record the last two good
  checkpoints;
* ``load_state_dict`` verifies the manifest + checksums and, on a corrupt
  or interrupted checkpoint, falls back through the pointer chain to the
  last-good checkpoint (counted in ``checkpoint.fallbacks_total``,
  logged). A kill injected mid-save (``FaultSchedule.kill`` at the
  ``checkpoint.write``/``checkpoint.commit`` sites) therefore leaves the
  previous checkpoint loadable — proven by tests/test_resilience.py.
  ``verify=False`` skips verification (and fallback) for pre-manifest
  legacy directories.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor, to_tensor
from ... import observability as _obs
from ...resilience import faults as _faults

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict",
           "wait_async_saves", "CheckpointCorruptError", "verify_checkpoint"]

_log = logging.getLogger(__name__)

MANIFEST_VERSION = 1
_MANIFEST = "manifest.json"
_LATEST = "latest"
_LATEST_PREV = "latest.prev"


class CheckpointCorruptError(RuntimeError):
    """Raised when a checkpoint fails verification and no last-good
    fallback can be loaded."""


class _CorruptCheckpoint(Exception):
    """Internal: this candidate failed verification, try the next."""


def _spec_of(t: Tensor):
    arr = t._data
    try:
        sh = arr.sharding
        if hasattr(sh, "spec"):
            return [list(p) if isinstance(p, tuple) else p for p in sh.spec]
    except Exception:
        pass  # tracer / committed-elsewhere array: no readable sharding spec
    return None


_ASYNC: List[Any] = []  # pending (ckptr | thread) handles
# pointer-rotation ordering: async commits finish in arbitrary order, and
# a slow OLD save completing after a newer one must not roll ``latest``
# back; every save takes a sequence number at entry and the rotation
# skips stale ones. _LOCK also guards the _ASYNC handle list.
_LOCK = threading.Lock()
_SAVE_SEQ = itertools.count(1)
_last_committed_seq = 0


# ---------------------------------------------------------------------------
# atomic file plumbing + manifest / pointer helpers
# ---------------------------------------------------------------------------

def _fsync_dir(dirname: str) -> None:
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass  # fs without dir fsync (e.g. some network mounts): best effort
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + ``os.replace``: readers see the old file or the new
    file, never a torn write. The tmp name is pid-unique because
    multi-process saves write the same bookkeeping files concurrently
    (same content — last replace wins)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _crc_of(arr) -> Optional[int]:
    """CRC32 of the array's logical row-major bytes; None when this
    process cannot see the whole array (multi-host shards) or the value
    is not host-copyable (tracer) — unverifiable, recorded as such."""
    try:
        if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
            return None
        host = np.ascontiguousarray(np.asarray(arr))
    except Exception:
        return None
    return zlib.crc32(host.tobytes()) & 0xFFFFFFFF


def _pointer_paths(path: str) -> Tuple[str, str, str]:
    norm = os.path.normpath(os.path.abspath(path))
    parent = os.path.dirname(norm)
    return (os.path.join(parent, _LATEST),
            os.path.join(parent, _LATEST_PREV),
            os.path.basename(norm))


def _read_pointer(p: str) -> Optional[str]:
    try:
        with open(p, "rb") as f:
            name = f.read().decode().strip()
        return name or None
    except OSError:
        return None  # pointer absent: no checkpoint committed here yet


def _update_latest(path: str, seq: int) -> None:
    """Rotate the last-good pointers after a COMMITTED save: ``latest``
    names this checkpoint, ``latest.prev`` whatever ``latest`` named
    before (the fallback when the newest one is later found corrupt).
    ``seq`` orders commits within this process: an older async save
    finishing late is skipped instead of rolling ``latest`` backward."""
    global _last_committed_seq
    latest_p, prev_p, name = _pointer_paths(path)
    with _LOCK:
        if seq < _last_committed_seq:
            _log.warning(
                "checkpoint: save of %s (seq %d) committed after a newer "
                "save (seq %d); leaving the latest pointer alone",
                path, seq, _last_committed_seq)
            return
        _last_committed_seq = seq
        old = _read_pointer(latest_p)
        if old and old != name:
            _atomic_write(prev_p, old.encode())
        _atomic_write(latest_p, name.encode())


def _last_good_candidates(path: str) -> List[str]:
    """Fallback chain for ``path``: the pointer targets in the same parent
    directory, newest first, excluding ``path`` itself."""
    latest_p, prev_p, name = _pointer_paths(path)
    parent = os.path.dirname(os.path.normpath(os.path.abspath(path)))
    out: List[str] = []
    for ptr in (latest_p, prev_p):
        target = _read_pointer(ptr)
        if target and target != name:
            cand = os.path.join(parent, target)
            if os.path.isdir(cand) and cand not in out:
                out.append(cand)
    return out


def _read_manifest(path: str) -> Dict[str, Any]:
    mpath = os.path.join(path, _MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except OSError as e:
        raise _CorruptCheckpoint(
            "no committed manifest.json (interrupted or pre-manifest "
            f"save): {e}") from e
    except (ValueError, json.JSONDecodeError) as e:
        raise _CorruptCheckpoint(f"unparsable manifest.json: {e}") from e
    if not isinstance(manifest.get("arrays"), dict):
        raise _CorruptCheckpoint("manifest.json missing 'arrays' table")
    return manifest


def verify_checkpoint(path: str) -> Dict[str, Any]:
    """Committed-manifest probe (no array IO): returns the manifest or
    raises :class:`CheckpointCorruptError`. Harness/tooling surface."""
    try:
        return _read_manifest(path)
    except _CorruptCheckpoint as e:
        raise CheckpointCorruptError(str(e)) from e


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def _globalize_host_local(arrays: Dict[str, Any]) -> None:
    """Multi-process saves can only serialize GLOBAL arrays. Host-local
    entries (single-device scalars like step counters, or values created
    outside the mesh) are converted IN PLACE to globally-replicated arrays.
    Every process must hold the same value — that is checked with ONE
    pytree allgather over all such keys (not one collective per key), and
    the written value is rank 0's (deterministic: never whichever replica
    orbax picks as primary)."""
    if jax.process_count() == 1:
        return
    local = {k: np.asarray(a) for k, a in arrays.items()
             if a.is_fully_addressable}
    if not local:
        return
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    gathered = multihost_utils.process_allgather(local)
    mesh = Mesh(np.array(jax.devices()), ("_ckpt",))
    repl = NamedSharding(mesh, P())
    for k, host in local.items():
        g = np.asarray(gathered[k])
        exact = not np.issubdtype(host.dtype, np.inexact)
        same = np.array_equal(g, np.broadcast_to(g[0:1], g.shape)) if exact \
            else np.allclose(g, g[0:1], equal_nan=True)
        if not same:
            raise ValueError(
                f"checkpoint key {k!r} is a host-local array whose value "
                "differs across processes; make it a global (mesh-placed) "
                "array or reconcile it before save_state_dict")
        canonical = g[0]  # rank 0's value: deterministic content
        arrays[k] = jax.make_array_from_callback(
            canonical.shape, repl,
            lambda idx, _c=canonical: _c[idx])


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False) -> None:
    _faults.fault_point("checkpoint.save")
    _obs.inc("checkpoint.saves_total")
    os.makedirs(path, exist_ok=True)
    # the directory is UNCOMMITTED for the whole write window: a stale
    # manifest from an earlier save into the same path must not vouch for
    # the new arrays if this save dies partway
    try:
        os.remove(os.path.join(path, _MANIFEST))
    except OSError:
        pass  # first save into this directory: nothing to invalidate
    flat = _flatten("", state_dict)
    meta = {}
    arrays: Dict[str, Any] = {}
    for k, v in flat.items():
        if isinstance(v, Tensor):
            # raw (possibly sharded) jax.Array — orbax writes per-shard;
            # no np.asarray host gather here
            arrays[k] = v._data
            meta[k] = {"shape": list(v._data.shape),
                       "dtype": str(v._data.dtype),
                       "spec": _spec_of(v)}
        else:
            meta[k] = {"value": v}
    _globalize_host_local(arrays)
    _atomic_write(os.path.join(path, "metadata.json"),
                  json.dumps(meta).encode())
    _faults.fault_point("checkpoint.write")
    seq = next(_SAVE_SEQ)

    def _commit(fmt: str) -> None:
        # checksums are taken at commit time from the arrays as handed to
        # the writer (jax.Arrays are immutable, so async completion
        # threads compute them off the training thread), one at a time —
        # a transient host copy per array, never the whole tree at once;
        # unaddressable shards record null
        entries = {k: {"crc32": _crc_of(a), "dtype": str(a.dtype),
                       "shape": list(a.shape)} for k, a in arrays.items()}
        _faults.fault_point("checkpoint.commit")
        _atomic_write(os.path.join(path, _MANIFEST), json.dumps(
            {"version": MANIFEST_VERSION, "format": fmt,
             "arrays": entries}).encode())
        _update_latest(path, seq)

    try:
        import orbax.checkpoint as ocp
    except Exception:
        ocp = None

    if ocp is not None:
        if async_save:
            ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            ckptr.save(os.path.join(path, "arrays"), arrays, force=True)

            def _wait_and_commit():
                ckptr.wait_until_finished()
                try:
                    ckptr.close()
                except Exception:
                    pass  # double-close of a finished async handle is benign
                _commit("orbax")

            t = threading.Thread(target=_wait_and_commit, daemon=True)
            t.start()
            with _LOCK:
                _ASYNC.append(t)
        else:
            ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).save(
                os.path.join(path, "arrays"), arrays, force=True)
            _commit("orbax")
        return

    # fallback without orbax: single-file npz (full host gather — small
    # states only; orbax is the supported path), written atomically so a
    # kill mid-write leaves no half npz behind the committed name
    def _write():
        final = os.path.join(path, "arrays.npz")
        tmp = f"{final}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **{k: np.asarray(a) for k, a in arrays.items()})
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        _fsync_dir(path)
        _commit("npz")

    if async_save:
        t = threading.Thread(target=_write, daemon=True)
        t.start()
        with _LOCK:
            _ASYNC.append(t)
    else:
        _write()


def wait_async_saves() -> None:
    # snapshot under the lock, join OUTSIDE it: completion threads take
    # _LOCK themselves to rotate the latest pointer
    with _LOCK:
        pending = list(_ASYNC)
        _ASYNC.clear()
    for h in pending:
        if hasattr(h, "wait_until_finished"):
            h.wait_until_finished()
            try:
                h.close()
            except Exception:
                pass  # double-close of a finished async handle is benign
        else:
            h.join()


def async_save_state_dict(state_dict, path, **kw):
    return save_state_dict(state_dict, path, async_save=True, **kw)


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------

def _target_sharding(t: Tensor):
    """The destination's concrete sharding (NamedSharding for mesh-placed
    tensors, SingleDeviceSharding for plain ones) — orbax restores exactly
    the shards it needs for it; a checkpoint written by OTHER processes'
    devices can only be read by passing a concrete local sharding."""
    try:
        sh = t._data.sharding
        if isinstance(sh, jax.sharding.Sharding):
            return sh
    except Exception:
        pass  # tracer payload: sharding is unreadable, caller falls back
    return None


def _read_arrays(path: str, flat: Dict[str, Tensor], meta: Dict[str, Any]
                 ) -> Dict[str, Any]:
    """Restore exactly the target tree's arrays; any IO/parse failure in
    the payload is a verification failure (corrupt candidate), not a user
    error."""
    arrays_dir = os.path.join(path, "arrays")
    if os.path.isdir(arrays_dir):
        import orbax.checkpoint as ocp
        # PARTIAL restore: only the target tree's keys are read (item
        # template + transforms={} makes orbax skip the rest) — a
        # model-only load from a checkpoint that also holds optimizer m/v
        # never materializes the optimizer state, and each restored key
        # reads exactly the shards its destination sharding needs
        # (reshard-on-load)
        restore_args = {}
        item = {}
        for k, t in flat.items():
            sh = _target_sharding(t)
            if sh is not None:
                restore_args[k] = ocp.ArrayRestoreArgs(sharding=sh)
            else:
                restore_args[k] = ocp.RestoreArgs()
            try:
                item[k] = jax.ShapeDtypeStruct(
                    tuple(meta[k]["shape"]), np.dtype(meta[k]["dtype"]),
                    sharding=sh)
            except TypeError:
                item[k] = jax.ShapeDtypeStruct(
                    tuple(meta[k]["shape"]), np.dtype(meta[k]["dtype"]))
        try:
            return ocp.Checkpointer(ocp.PyTreeCheckpointHandler()).restore(
                arrays_dir, item=item, restore_args=restore_args,
                transforms={})
        except Exception as e:
            raise _CorruptCheckpoint(
                f"array restore failed ({type(e).__name__}: {e})") from e
    try:
        npz = np.load(os.path.join(path, "arrays.npz"))
        return {k: npz[k] for k in npz.files}
    except Exception as e:
        raise _CorruptCheckpoint(
            f"array payload unreadable ({type(e).__name__}: {e})") from e


def _load_into(flat: Dict[str, Tensor], path: str, verify: bool) -> None:
    try:
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
    except OSError as e:
        raise _CorruptCheckpoint(f"metadata.json unreadable: {e}") from e
    except (ValueError, json.JSONDecodeError) as e:
        raise _CorruptCheckpoint(f"metadata.json unparsable: {e}") from e

    # USER errors (wrong tree for this checkpoint), never fallback bait
    for k in flat:
        if k not in meta or "value" in meta.get(k, {}):
            raise KeyError(f"checkpoint at {path} has no entry {k!r}")
        src_shape = meta[k]["shape"]
        if list(src_shape) != list(flat[k]._data.shape):
            raise ValueError(
                f"shape mismatch for {k}: checkpoint {src_shape} vs target "
                f"{tuple(flat[k]._data.shape)}")

    manifest = _read_manifest(path) if verify else None
    arrays = _read_arrays(path, flat, meta)

    if manifest is not None:
        table = manifest["arrays"]
        for k in flat:
            ent = table.get(k)
            if ent is None:
                raise _CorruptCheckpoint(
                    f"key {k!r} absent from the committed manifest")
            want = ent.get("crc32")
            if want is None:
                continue  # recorded unverifiable (multi-host shard)
            got = _crc_of(arrays[k])
            if got is not None and got != int(want):
                _obs.inc("checkpoint.crc_mismatches_total")
                raise _CorruptCheckpoint(
                    f"checksum mismatch for {k!r} "
                    f"(manifest {int(want)}, payload {got})")

    for k, tgt in flat.items():
        src = arrays[k]
        if not getattr(tgt._data, "_committed", True):
            # the destination is UNCOMMITTED (plain single-host state):
            # restore it uncommitted too — via host, because a jax.Array
            # read back by orbax under an explicit sharding is committed
            # and stays committed through jnp.asarray. device_put (or
            # adopting the committed source) would pin the tensor to one
            # device, and a later whole-program capture (to_static /
            # step_capture functionalization carries EVERY registry
            # tensor) commits its entire state carry to that device's
            # placement — which then conflicts with mesh-committed arrays
            # sharing a jit. "Reshard to the destination's placement"
            # includes preserving its non-placement.
            arr = jax.numpy.asarray(np.asarray(src).astype(tgt._data.dtype))
        elif isinstance(src, jax.Array) and _target_sharding(tgt) is not None \
                and src.sharding == tgt._data.sharding:
            arr = src.astype(tgt._data.dtype) \
                if src.dtype != tgt._data.dtype else src
        else:
            host = np.asarray(src)
            try:
                arr = jax.device_put(host.astype(tgt._data.dtype),
                                     tgt._data.sharding)
            except Exception:
                arr = jax.numpy.asarray(host.astype(tgt._data.dtype))
        tgt._set_data(arr)


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False,
                    verify: bool = True, fallback: bool = True) -> None:
    """Load INTO ``state_dict``'s tensors (paddle semantics), resharding to
    each destination tensor's current placement.

    With ``verify`` (default) the checkpoint must carry a committed
    manifest and every verifiable array must match its CRC32; a candidate
    that fails moves the load down the last-good pointer chain
    (``fallback``), counting ``checkpoint.fallbacks_total``. Missing-key /
    shape-mismatch errors are USER errors and always raise immediately.
    ``verify=False`` restores the pre-manifest behavior for legacy
    directories: no verification, no fallback, and IO failures surface
    with their original types (``FileNotFoundError``, ...)."""
    flat = {k: t for k, t in _flatten("", state_dict).items()
            if isinstance(t, Tensor)}
    if not verify:
        # legacy path: no manifest check, no fallback — and the original
        # error surface (FileNotFoundError etc.), not a corruption wrap
        try:
            _load_into(flat, path, verify=False)
        except _CorruptCheckpoint as e:
            raise e.__cause__ if e.__cause__ is not None \
                else CheckpointCorruptError(str(e))
        _obs.inc("checkpoint.loads_total")
        return
    candidates = [path]
    if fallback:
        candidates += _last_good_candidates(path)
    last_reason: Optional[str] = None
    for i, p in enumerate(candidates):
        try:
            _load_into(flat, p, verify=True)
        except _CorruptCheckpoint as e:
            _obs.inc("checkpoint.verification_failures_total")
            more = i + 1 < len(candidates)
            if more:
                # counts actual FALLBACKS (moving to the next candidate),
                # not bare verification failures — alerting keys on this
                _obs.inc("checkpoint.fallbacks_total")
            _log.error(
                "checkpoint: %s failed verification (%s)%s", p, e,
                "; falling back to last-good" if more else "")
            last_reason = f"{p}: {e}"
            continue
        if i > 0:
            _log.warning(
                "checkpoint: restored last-good %s after %s failed "
                "verification (checksums verified)", p, path)
        _obs.inc("checkpoint.loads_total")
        return
    raise CheckpointCorruptError(
        f"no loadable checkpoint ({last_reason}); for a legacy pre-manifest "
        "directory pass verify=False")


def _flatten(prefix: str, obj) -> Dict[str, Any]:
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(f"{prefix}.{k}" if prefix else str(k), v))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            out.update(_flatten(f"{prefix}.{i}", v))
    else:
        out[prefix] = obj
    return out
