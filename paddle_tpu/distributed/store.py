"""TCPStore: rank-0-hosted KV rendezvous store.

Parity surface: ``paddle.distributed.TCPStore`` / the reference's C++ store
(paddle/phi/core/distributed/store/ — no line cites: reference mount was
empty, see SURVEY.md provenance). The heavy lifting is the native C++ server/
client in ``paddle_tpu/_native``; a pure-Python implementation of the same
wire protocol (see tcp_store.cc header comment) is the fallback, and the two
interoperate. On TPU the rendezvous role is normally played by
``jax.distributed.initialize``'s coordination service; TCPStore remains for
API parity and for launcher/elastic bookkeeping that wants a plain KV store.

Failure handling (PR 5): the pure-python client dials under the
``store.connect`` :class:`~paddle_tpu.resilience.RetryPolicy` and every
``get``/``wait``/``set`` round-trip reconnects once on a connection torn
down mid-request (``store.reconnects_total``), so a restarted store host
or reaped idle socket surfaces as one transparent retry instead of a raw
socket error; ``store.connect``/``store.request`` are fault-injection
sites for driving those paths deterministically in tests.
"""

from __future__ import annotations

import ctypes
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Union

from .. import _native
from .. import observability as _obs
from .. import resilience as _resil
from ..resilience import faults as _faults

_log = logging.getLogger(__name__)

__all__ = ["TCPStore", "Store"]

_OPS = {"set": 1, "get": 2, "add": 3, "wait": 4, "check": 5, "del": 6,
        "numkeys": 7}


# ---------------------------------------------------------------------------
# pure-Python protocol server (fallback; interoperates with the C++ client)
# ---------------------------------------------------------------------------
class _PyServerState:
    def __init__(self) -> None:
        self.kv: Dict[bytes, bytes] = {}
        self.cond = threading.Condition()


class _PyHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        st: _PyServerState = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

        def read_full(n: int) -> Optional[bytes]:
            buf = b""
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                if not chunk:
                    return None
                buf += chunk
            return buf

        while True:
            hdr = read_full(5)
            if hdr is None:
                return
            op, klen = struct.unpack("<BI", hdr)
            key = read_full(klen) if klen else b""
            vlen_b = read_full(8)
            if key is None or vlen_b is None:
                return
            (vlen,) = struct.unpack("<Q", vlen_b)
            val = read_full(vlen) if vlen else b""
            if val is None:
                return
            status, out = 0, b""
            if op == _OPS["set"]:
                with st.cond:
                    st.kv[key] = val
                    st.cond.notify_all()
            elif op in (_OPS["get"], _OPS["wait"]):
                (timeout_ms,) = struct.unpack("<Q", val) if len(val) == 8 else (0,)
                deadline = time.monotonic() + timeout_ms / 1e3
                with st.cond:
                    while key not in st.kv:
                        left = deadline - time.monotonic()
                        if left <= 0 or not st.cond.wait(left):
                            if key not in st.kv:
                                break
                    if key not in st.kv:
                        status = 1
                    elif op == _OPS["get"]:
                        out = st.kv[key]
            elif op == _OPS["add"]:
                (delta,) = struct.unpack("<q", val) if len(val) == 8 else (0,)
                with st.cond:
                    cur = struct.unpack("<q", st.kv[key])[0] \
                        if len(st.kv.get(key, b"")) == 8 else 0
                    out = struct.pack("<q", cur + delta)
                    st.kv[key] = out
                    st.cond.notify_all()
            elif op == _OPS["check"]:
                with st.cond:
                    status = 0 if key in st.kv else 1
            elif op == _OPS["del"]:
                with st.cond:
                    status = 0 if st.kv.pop(key, None) is not None else 1
                    st.cond.notify_all()
            elif op == _OPS["numkeys"]:
                with st.cond:
                    out = struct.pack("<q", len(st.kv))
            else:
                status = 1
            sock.sendall(struct.pack("<BQ", status, len(out)) + out)


class _PyServer:
    def __init__(self, port: int):
        class _TS(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _TS(("0.0.0.0", port), _PyHandler)
        self._srv.state = _PyServerState()  # type: ignore[attr-defined]
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class _PyClient:
    def __init__(self, host: str, port: int, timeout: float):
        self._host, self._port = host, port
        self._timeout = timeout
        self._mu = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._connect(timeout)

    def _connect(self, timeout: float) -> None:
        """Dial (or re-dial) the store under the ``store.connect`` policy
        (jittered 50ms→500ms backoff, ``PADDLE_TPU_RETRY_STORE_CONNECT_*``
        overrides) for up to ``timeout`` seconds."""
        policy = _resil.get_policy("store.connect", base_delay=0.05,
                                   multiplier=1.6, max_delay=0.5,
                                   jitter=0.25)
        for attempt in policy.start(deadline=timeout):
            try:
                _faults.fault_point("store.connect")
                sock = socket.create_connection((self._host, self._port),
                                                timeout=5)
                break
            except OSError as e:
                try:
                    attempt.fail(e)
                except OSError as last:
                    raise ConnectionError(
                        f"TCPStore connect to {self._host}:{self._port} "
                        f"failed") from last
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def _read_full(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("TCPStore connection closed")
            buf += chunk
        return buf

    def request(self, op: int, key: bytes, val: bytes) -> tuple:
        """One wire round-trip; reconnects ONCE on a connection torn down
        mid-request (server restarted / idle socket reaped) instead of
        surfacing the raw socket error to callers. CAVEAT: the request is
        re-sent after reconnecting, so a non-idempotent ``add`` whose
        first send reached a server that then answered into the dead
        socket could double-apply — acceptable for rendezvous counters
        where the realistic failure is the server dying (state gone)
        rather than the lone socket."""
        msg = (struct.pack("<BI", op, len(key)) + key +
               struct.pack("<Q", len(val)) + val)
        with self._mu:
            for attempt_no in (1, 2):
                try:
                    _faults.fault_point("store.request")
                    self._sock.sendall(msg)
                    self._sock.settimeout(None)
                    status, vlen = struct.unpack("<BQ", self._read_full(9))
                    out = self._read_full(vlen) if vlen else b""
                    return status, out
                except (ConnectionError, BrokenPipeError) as e:
                    # ConnectionError covers ConnectionResetError and the
                    # clean-EOF "connection closed" raise in _read_full
                    if attempt_no == 2:
                        raise
                    _obs.inc("store.reconnects_total")
                    _log.warning(
                        "TCPStore: connection lost mid-request (%s: %s); "
                        "reconnecting once", type(e).__name__, e)
                    try:
                        self._sock.close()
                    except OSError:
                        pass  # half-dead socket: close is best-effort
                    self._connect(self._timeout)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass  # already closed by the peer/GC: close is best-effort


# ---------------------------------------------------------------------------
# public store API
# ---------------------------------------------------------------------------
class Store:
    """Abstract store interface (reference: phi::distributed::Store)."""

    def set(self, key: str, value) -> None:
        raise NotImplementedError

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError

    def add(self, key: str, delta: int) -> int:
        raise NotImplementedError

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        raise NotImplementedError


class TCPStore(Store):
    """Rank-0-hosted TCP key-value store.

    ``TCPStore(host, port, is_master=True)`` starts the server (native C++
    when available) and connects a client; non-masters just connect. ``port=0``
    on the master picks an ephemeral port (read it back from ``.port``).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 30.0, use_native: Optional[bool] = None):
        self.host = host
        self.is_master = is_master
        self.world_size = world_size
        self.timeout = timeout
        native = _native.available() if use_native is None else (
            use_native and _native.available())
        self._native = native
        self._server = None
        self._server_native = None
        self._client = None  # set before any fallible step so close() is
        # always safe, even when __init__ raises partway
        if is_master:
            if native:
                self._server_native = _native.lib.pt_store_server_start(port)
                if not self._server_native:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                self.port = _native.lib.pt_store_server_port(self._server_native)
            else:
                self._server = _PyServer(port)
                self.port = self._server.port
        else:
            self.port = port
        self._barrier_rounds: Dict[str, int] = {}
        try:
            # resolve to an IPv4 literal for the native client (inet_pton);
            # resolution failure must be loud — a fallback address would
            # rendezvous with the wrong store on multi-host jobs
            try:
                addr = socket.gethostbyname(host)
            except OSError as e:
                raise ConnectionError(
                    f"TCPStore: cannot resolve {host!r}") from e
            if native:
                self._client = _native.lib.pt_store_client_new(
                    addr.encode(), self.port, timeout)
                if not self._client:
                    raise ConnectionError(
                        f"TCPStore connect to {addr}:{self.port} failed")
            else:
                self._client = _PyClient(addr, self.port, timeout)
        except Exception:
            self.close()  # don't leak a started server on a failed init
            raise

    # -- ops ---------------------------------------------------------------
    def set(self, key: str, value: Union[bytes, str]) -> None:
        data = value.encode() if isinstance(value, str) else bytes(value)
        if self._native:
            buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
                else None
            rc = _native.lib.pt_store_set(self._client, key.encode(), buf,
                                          len(data))
            if rc != 0:
                raise ConnectionError("TCPStore set failed")
        else:
            status, _ = self._client.request(_OPS["set"], key.encode(), data)
            if status != 0:
                raise ConnectionError("TCPStore set failed")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        t = self.timeout if timeout is None else timeout
        if self._native:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = _native.lib.pt_store_get(self._client, key.encode(), t,
                                         ctypes.byref(out))
            if n == -1:
                raise TimeoutError(f"TCPStore get({key!r}) timed out")
            if n < 0:
                raise ConnectionError("TCPStore get transport error")
            try:
                return ctypes.string_at(out, n)
            finally:
                _native.lib.pt_store_buf_free(out)
        status, val = self._client.request(
            _OPS["get"], key.encode(), struct.pack("<Q", int(t * 1e3)))
        if status != 0:
            raise TimeoutError(f"TCPStore get({key!r}) timed out")
        return val

    def add(self, key: str, delta: int = 1) -> int:
        if self._native:
            v = _native.lib.pt_store_add(self._client, key.encode(), delta)
            if v == -(2 ** 63):
                raise ConnectionError("TCPStore add failed")
            return int(v)
        status, out = self._client.request(
            _OPS["add"], key.encode(), struct.pack("<q", delta))
        if status != 0 or len(out) != 8:
            raise ConnectionError("TCPStore add failed")
        return struct.unpack("<q", out)[0]

    def wait(self, keys: Union[str, List[str]],
             timeout: Optional[float] = None) -> None:
        t = self.timeout if timeout is None else timeout
        for key in ([keys] if isinstance(keys, str) else keys):
            if self._native:
                if _native.lib.pt_store_wait(self._client, key.encode(), t) != 0:
                    raise TimeoutError(f"TCPStore wait({key!r}) timed out")
            else:
                status, _ = self._client.request(
                    _OPS["wait"], key.encode(), struct.pack("<Q", int(t * 1e3)))
                if status != 0:
                    raise TimeoutError(f"TCPStore wait({key!r}) timed out")

    def check(self, key: str) -> bool:
        if self._native:
            return bool(_native.lib.pt_store_check(self._client, key.encode()))
        status, _ = self._client.request(_OPS["check"], key.encode(), b"")
        return status == 0

    def delete_key(self, key: str) -> bool:
        if self._native:
            return bool(_native.lib.pt_store_del(self._client, key.encode()))
        status, _ = self._client.request(_OPS["del"], key.encode(), b"")
        return status == 0

    def num_keys(self) -> int:
        if self._native:
            return int(_native.lib.pt_store_num_keys(self._client))
        _, out = self._client.request(_OPS["numkeys"], b"", b"")
        return struct.unpack("<q", out)[0]

    # -- barrier (built on add/wait, the reference's pattern) --------------
    def barrier(self, name: str = "barrier", timeout: Optional[float] = None
                ) -> None:
        # per-name round counter so the same barrier name is reusable: each
        # round gets fresh keys (all ranks call barrier the same number of
        # times, so local round counts agree across ranks)
        rnd = self._barrier_rounds.get(name, 0)
        self._barrier_rounds[name] = rnd + 1
        arrived = self.add(f"__{name}_{rnd}__count", 1)
        if arrived == self.world_size:
            self.set(f"__{name}_{rnd}__go", b"1")
        self.wait(f"__{name}_{rnd}__go", timeout)

    def close(self) -> None:
        if self._native:
            if self._client:
                _native.lib.pt_store_client_free(self._client)
                self._client = None
            if self._server_native:
                _native.lib.pt_store_server_stop(self._server_native)
                self._server_native = None
        else:
            if self._client:
                self._client.close()
                self._client = None
            if self._server:
                self._server.stop()
                self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter-teardown close: nothing left to signal to
