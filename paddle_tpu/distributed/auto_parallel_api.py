"""Auto-parallel (DTensor) API.

Parity surface: paddle.distributed auto-parallel surface —
``ProcessMesh``, placements (``Shard(d)``, ``Replicate()``, ``Partial()``),
``shard_tensor``, ``dtensor_from_fn``, ``reshard``, ``shard_layer``
(upstream python/paddle/distributed/auto_parallel/ + C++ DistTensor in
paddle/phi/core/distributed/auto_parallel/). TPU-native: a DistTensor IS a
jax array with a NamedSharding — placements translate 1:1 to PartitionSpec
entries, reshard is ``device_put``, and the reference's per-op SPMD rules are
XLA GSPMD propagation.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, to_tensor
from ..nn.layer import Layer

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "dtensor_from_fn", "reshard", "shard_layer", "get_mesh", "set_mesh"]


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Partial(Placement):
    """Pending-reduction placement. XLA tracks partial values internally; at
    the API boundary we materialize the reduction (device_put cannot express
    'partial'), which matches reshard(Partial->Replicate) semantics."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """Parity: paddle.distributed.ProcessMesh(mesh, dim_names)."""

    def __init__(self, mesh: Union[Sequence, np.ndarray],
                 dim_names: Optional[Sequence[str]] = None, shape=None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self.dim_names = list(dim_names)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        devs = jax.devices()
        dev_arr = np.array([devs[i % len(devs)] for i in self.process_ids]
                           ).reshape(arr.shape)
        self.jax_mesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def get_dim_size(self, name: str) -> int:
        return self.shape[self.dim_names.index(name)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def _placements_to_spec(placements: Sequence[Placement], mesh: ProcessMesh,
                        ndim: int) -> P:
    """placements[i] says how mesh dim i maps onto tensor dims."""
    entries: List = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            axis_name = mesh.dim_names[mesh_dim]
            cur = entries[pl.dim]
            if cur is None:
                entries[pl.dim] = axis_name
            elif isinstance(cur, tuple):
                entries[pl.dim] = cur + (axis_name,)
            else:
                entries[pl.dim] = (cur, axis_name)
        # Replicate/Partial: no entry
    return P(*entries)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Parity: paddle.distributed.shard_tensor."""
    t = data if isinstance(data, Tensor) else to_tensor(data, dtype=dtype)
    spec = _placements_to_spec(placements, mesh, t._data.ndim)
    arr = jax.device_put(t._data, NamedSharding(mesh.jax_mesh, spec))
    out = Tensor(arr, stop_gradient=t.stop_gradient if stop_gradient is None
                 else stop_gradient, name=t.name)
    out.placements = list(placements)
    out.process_mesh = mesh
    return out


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs) -> Tensor:
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Parity: paddle.distributed.reshard — relayout via device_put (XLA
    emits the minimal collective: all-gather / all-to-all / slice)."""
    spec = _placements_to_spec(placements, mesh, dist_tensor._data.ndim)
    arr = jax.device_put(dist_tensor._data, NamedSharding(mesh.jax_mesh, spec))
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient)
    out.placements = list(placements)
    out.process_mesh = mesh
    return out


def shard_layer(layer: Layer, process_mesh: ProcessMesh,
                shard_fn=None, input_fn=None, output_fn=None) -> Layer:
    """Parity: paddle.distributed.shard_layer — apply shard_fn(name, layer,
    mesh) to every sublayer (it calls shard_tensor on the params it wants
    distributed); default replicates every parameter on the mesh."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for p in sublayer.parameters(include_sublayers=False):
                p._set_data(jax.device_put(
                    p._data, NamedSharding(mesh.jax_mesh, P())))

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather a distributed tensor to a fully-replicated dense tensor
    (reference: paddle.distributed.unshard_dtensor)."""
    mesh = getattr(dist_tensor, "process_mesh", None)
    if mesh is None:
        return Tensor(dist_tensor._data,
                      stop_gradient=dist_tensor.stop_gradient)
    ndim = dist_tensor._data.ndim
    arr = jax.device_put(dist_tensor._data,
                         NamedSharding(mesh.jax_mesh, P(*([None] * ndim))))
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient,
                 name=dist_tensor.name)
    return out


def to_distributed(model, optimizer=None, dataloader=None, device_num=None,
                   node_num=1, config=None):
    """Experimental one-call distribution (reference:
    paddle.distributed.to_distributed): places every parameter on the global
    mesh (replicated — data parallel by default; pass a parallelize config
    for TP/sharding) and returns the inputs rewrapped."""
    mesh = get_mesh()
    if mesh is None:
        import numpy as np

        devs = jax.devices()
        n = device_num or len(devs)
        mesh = ProcessMesh(np.arange(n).reshape(-1), dim_names=["dp"])
        set_mesh(mesh)
    if config:
        from .auto_parallel.parallelize import parallelize as _par
        out = _par(model, optimizer, mesh=mesh, config=config)
        model = out[0] if isinstance(out, tuple) else out
        if isinstance(out, tuple) and optimizer is not None:
            optimizer = out[1]
    else:
        shard_layer(model, mesh)
    results = [model]
    if optimizer is not None:
        results.append(optimizer)
    if dataloader is not None:
        results.append(dataloader)
    return tuple(results) if len(results) > 1 else results[0]
