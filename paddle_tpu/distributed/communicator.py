"""Async/Geo communicator for sparse-embedding training.

Parity surface: the reference PS ``Communicator``
(upstream paddle/fluid/distributed/ps/service/communicator/ — a background
thread that batches gradient "sends" so trainers never block on the table
update, with ASYNC (apply every batch window) and GEO (apply parameter
DELTAS every k steps) modes). TPU-native re-scope per the north star
("PS → ICI allreduce path"): there is no brpc table service — the tables
are mesh-sharded dense tensors (``ShardedEmbedding``) living on device, and
the communicator's value is the ASYNCHRONY contract: ``push_sparse`` hands
a gradient off to a bounded queue and returns immediately; a daemon thread
applies batched updates to the table; ``pull_sparse``/``barrier`` give the
read-your-writes points. GEO mode accumulates k pushes and applies their
SUM once — the same staleness/traffic trade the reference's
GeoCommunicator makes.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Communicator", "register_sparse_table", "registered_tables"]

# name -> weakly-held table tensor; ShardedEmbedding self-registers here so
# fleet.init_worker can hand the worker's sparse tables to the Communicator
# without a manual init_with_ctx call
import weakref

_TABLE_REGISTRY: Dict[str, "weakref.ref"] = {}


def register_sparse_table(name: str, table: Tensor) -> None:
    _TABLE_REGISTRY[name] = weakref.ref(table)


def registered_tables() -> Dict[str, Tensor]:
    out = {}
    for name, ref in list(_TABLE_REGISTRY.items()):
        t = ref()
        if t is None:
            del _TABLE_REGISTRY[name]
        else:
            out[name] = t
    return out


class Communicator:
    """``Communicator(mode="async"|"geo"|"sync")`` over sharded tables.

    mode="sync"  — push applies inline (exact SGD; the default data path).
    mode="async" — pushes enqueue; a daemon thread applies them in arrival
                   order. Bounded queue gives backpressure instead of
                   unbounded staleness.
    mode="geo"   — pushes accumulate PER TABLE; a table flushes when its own
                   count reaches ``geo_k`` (reference GeoCommunicator tracks
                   per-table send deltas — a global count would stagger the
                   staleness window unpredictably as table count grows).
    """

    def __init__(self, mode: str = "async", send_queue_size: int = 32,
                 geo_k: int = 8, lr: float = 0.01, remote=None):
        mode = mode.lower()
        if mode not in ("sync", "async", "geo"):
            raise ValueError(f"unknown communicator mode {mode!r}")
        self.mode = mode
        self.lr = float(lr)
        self.geo_k = int(geo_k)
        # remote: a ps_service.PsClient — pushes/pulls cross the process
        # boundary to tables held by a PS SERVER process (the reference
        # BrpcPsClient seam) instead of mutating worker-local tables
        self._remote = remote
        if remote is not None:
            remote.lr = self.lr
        self._tables: Dict[str, Tensor] = {}
        self._table_dims: Dict[str, int] = {}
        self._queue: "queue.Queue" = queue.Queue(maxsize=send_queue_size)
        self._accum: Dict[str, List] = {}
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._lock = threading.Lock()
        self._pending = 0
        self._drained = threading.Condition(self._lock)
        self._error: Optional[BaseException] = None

    # -- lifecycle (reference: Communicator::Start/Stop) ---------------------
    def init_with_ctx(self, tables: Dict[str, Tensor]) -> None:
        """Register the named tables (sharded embedding weights). With a
        remote client, the worker's initial table values seed the SERVER's
        state (idempotent create: the first worker wins, reference
        load-once shards) and the worker keeps only name -> row width."""
        self._tables.update(tables)
        if self._remote is not None:
            import numpy as np
            for name, t in tables.items():
                arr = np.asarray(t._data)
                self._remote.create_table(name, arr)
                self._table_dims[name] = int(arr.shape[-1])

    def start(self) -> None:
        if self.mode != "async" or self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._running = False
            self._queue.put(None)
            self._thread.join(timeout=10)
            self._thread = None

    def is_running(self) -> bool:
        return self._running

    # -- data path -----------------------------------------------------------
    def push_sparse(self, table_name: str, ids, grad) -> None:
        """Hand a (ids, grad_rows) update to the table. async: returns
        immediately; geo: accumulates; sync: applies inline."""
        if table_name not in self._tables:
            raise KeyError(f"unknown table {table_name!r}")
        ids_a = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        g_a = grad._data if isinstance(grad, Tensor) else jnp.asarray(grad)
        if self.mode == "sync":
            self._apply(table_name, ids_a, g_a)
            return
        if self.mode == "geo":
            self._accum.setdefault(table_name, []).append((ids_a, g_a))
            if len(self._accum[table_name]) >= self.geo_k:
                self._flush_geo(table_name)
            return
        if self._error is not None:
            raise RuntimeError(
                "communicator applier died") from self._error
        if self._thread is None:
            raise RuntimeError(
                "async Communicator not started; call start() first")
        with self._lock:
            self._pending += 1
        self._queue.put((table_name, ids_a, g_a))

    def pull_sparse(self, table_name: str, ids) -> Tensor:
        """Read rows. async: drains pending pushes first so a worker reads
        its own writes (reference: pull blocks on the send queue). geo:
        reads STALE params without flushing the accumulation window — the
        k-step batching is the mode's point (reference GeoCommunicator)."""
        if self.mode == "async":
            self.barrier()
        ids_a = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        if self._remote is not None:
            import numpy as np
            rows = self._remote.pull(table_name, np.asarray(ids_a),
                                     self._table_dims[table_name])
            return Tensor(jnp.asarray(rows), stop_gradient=True)
        table = self._tables[table_name]
        return Tensor(table._data[ids_a], stop_gradient=True)

    def barrier(self) -> None:
        """Wait until every queued push has been applied."""
        if self.mode == "geo":
            self._flush_geo()
            return
        if self.mode != "async":
            return
        with self._drained:
            while self._pending > 0:
                if self._error is not None:
                    raise RuntimeError(
                        "communicator applier died") from self._error
                if self._thread is None or not self._thread.is_alive():
                    raise RuntimeError(
                        "communicator applier is not running with "
                        f"{self._pending} updates pending")
                self._drained.wait(timeout=0.1)
        if self._error is not None:
            raise RuntimeError(
                "communicator applier died") from self._error

    # -- internals -----------------------------------------------------------
    def _apply(self, name: str, ids, grad) -> None:
        if self._remote is not None:
            # ship (rows, values) across the process boundary; the server
            # applies the SGD scatter rule to ITS table state
            import numpy as np
            self._remote.push(name, np.asarray(ids), np.asarray(grad))
            return
        t = self._tables[name]
        # scatter-subtract; duplicate ids accumulate (segment-sum semantics,
        # the reference accessor's SGD rule)
        t._set_data(t._data.at[ids].add(-self.lr * grad))

    def _flush_geo(self, table_name: Optional[str] = None) -> None:
        """Apply accumulated deltas for one table (its k-window filled) or
        all tables (barrier). With a remote PS the window merges into ONE
        wire push (segment-summing duplicate ids) — the reference
        GeoCommunicator sends one merged delta per window, not k RPCs."""
        names = [table_name] if table_name is not None else list(self._accum)
        for name in names:
            pending = self._accum.pop(name, [])
            if not pending:
                continue
            if self._remote is not None and len(pending) > 1:
                import numpy as np
                ids_all = np.concatenate(
                    [np.asarray(i).reshape(-1) for i, _ in pending])
                g_all = np.concatenate(
                    [np.asarray(g).reshape(len(np.asarray(i).reshape(-1)), -1)
                     for i, g in pending])
                uniq, inv = np.unique(ids_all, return_inverse=True)
                merged = np.zeros((uniq.shape[0], g_all.shape[1]),
                                  g_all.dtype)
                np.add.at(merged, inv, g_all)
                self._apply(name, uniq, merged)
                continue
            for ids, g in pending:
                self._apply(name, ids, g)

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                break
            name, ids, g = item
            try:
                self._apply(name, ids, g)
            except BaseException as e:  # record; surface at barrier/push
                self._error = e
                with self._drained:
                    self._pending -= 1
                    self._drained.notify_all()
                return
            with self._drained:
                self._pending -= 1
                self._drained.notify_all()
