"""Cross-process parameter-server service.

Parity surface: the reference PS services
(upstream paddle/fluid/distributed/ps/service/ — BrpcPsServer holding table
shards, BrpcPsClient issuing push_sparse/pull_sparse RPCs, the Communicator
batching sends). TPU-native transport: instead of brpc, the job's own RPC
plane (``distributed.rpc`` — pickle-over-TCP with per-job HMAC, bootstrapped
through the rendezvous TCPStore) carries the requests; the SERVER PROCESS
holds table state as host numpy arrays (sparse tables are host-memory
objects in the reference too — device meshes are the collective path, the
PS path is explicitly the host-side one).

Role separation is real: ``fleet.init(role)`` on a SERVER process serves
these tables; WORKER processes never hold them — ``push_sparse`` ships
(rows, values) across the process boundary and ``pull_sparse`` reads the
server's current state (including its staleness under geo batching, which
is the semantics the Communicator contract promises).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["PsClient", "serve_stats", "reset_server_state"]

# ---------------------------------------------------------------------------
# server-side state (lives in the PS SERVER process; reached via rpc)
# ---------------------------------------------------------------------------

_TABLES: Dict[str, np.ndarray] = {}
_LOCK = threading.Lock()
_STATS = {"pushes": 0, "pulls": 0, "creates": 0}


def reset_server_state() -> None:
    with _LOCK:
        _TABLES.clear()
        _STATS.update(pushes=0, pulls=0, creates=0)


def _srv_create(name: str, value_bytes: bytes, shape: Tuple[int, ...],
                dtype: str) -> bool:
    """Install a table (idempotent: the first creator wins, matching the
    reference's load-once table shards)."""
    with _LOCK:
        if name not in _TABLES:
            _TABLES[name] = np.frombuffer(value_bytes, dtype=dtype) \
                .reshape(shape).copy()
            _STATS["creates"] += 1
    return True


def _srv_push(name: str, ids_bytes: bytes, grad_bytes: bytes,
              n: int, dim: int, lr: float) -> bool:
    """Apply an SGD scatter-update: table[ids] -= lr * grad. Duplicate ids
    accumulate (segment-sum semantics, the reference accessor's rule)."""
    with _LOCK:
        t = _TABLES[name]
        ids = np.frombuffer(ids_bytes, dtype=np.int64)
        g = np.frombuffer(grad_bytes, dtype=np.float32).reshape(n, dim)
        np.subtract.at(t, ids, lr * g.astype(t.dtype))
        _STATS["pushes"] += 1
    return True


def _srv_pull(name: str, ids_bytes: bytes) -> bytes:
    with _LOCK:
        t = _TABLES[name]
        ids = np.frombuffer(ids_bytes, dtype=np.int64)
        _STATS["pulls"] += 1
        return t[ids].tobytes()


def _srv_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_STATS)


def _srv_table_snapshot(name: str) -> Tuple[bytes, Tuple[int, ...], str]:
    """Test/introspection surface: the server's CURRENT table state —
    exactly what geo-staleness assertions need to observe."""
    with _LOCK:
        t = _TABLES[name]
        return t.tobytes(), t.shape, str(t.dtype)


def serve_stats() -> Dict[str, int]:
    """Server-local stats read (same process)."""
    return _srv_stats()


# ---------------------------------------------------------------------------
# worker-side client
# ---------------------------------------------------------------------------

class PsClient:
    """push/pull against tables living in a PS SERVER process.

    The analogue of the reference BrpcPsClient: every method is a remote
    call; nothing is cached worker-side (pulls observe the server's real,
    possibly-stale-under-geo state)."""

    def __init__(self, server: str, lr: float = 0.01):
        self.server = server
        self.lr = float(lr)

    def _rpc(self):
        from . import rpc
        return rpc

    def create_table(self, name: str, value) -> None:
        arr = np.asarray(value)
        self._rpc().rpc_sync(self.server, _srv_create,
                             args=(name, arr.tobytes(), arr.shape,
                                   str(arr.dtype)))

    def push(self, name: str, ids, grad, wait: bool = True):
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grad, np.float32).reshape(ids.shape[0], -1)
        rpc = self._rpc()
        args = (name, ids.tobytes(), g.tobytes(), g.shape[0], g.shape[1],
                self.lr)
        if wait:
            return rpc.rpc_sync(self.server, _srv_push, args=args)
        return rpc.rpc_async(self.server, _srv_push, args=args)

    def pull(self, name: str, ids, dim: int, dtype=np.float32) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        raw = self._rpc().rpc_sync(self.server, _srv_pull,
                                   args=(name, ids.tobytes()))
        return np.frombuffer(raw, dtype=dtype).reshape(ids.shape[0], dim)

    def table_snapshot(self, name: str) -> np.ndarray:
        raw, shape, dtype = self._rpc().rpc_sync(
            self.server, _srv_table_snapshot, args=(name,))
        return np.frombuffer(raw, dtype=dtype).reshape(shape)

    def stats(self) -> Dict[str, int]:
        return self._rpc().rpc_sync(self.server, _srv_stats)
