"""Cross-process parameter-server service.

Parity surface: the reference PS services
(upstream paddle/fluid/distributed/ps/service/ — BrpcPsServer holding table
shards, BrpcPsClient issuing push_sparse/pull_sparse RPCs, the Communicator
batching sends). TPU-native transport: instead of brpc, the job's own RPC
plane (``distributed.rpc`` — pickle-over-TCP with per-job HMAC, bootstrapped
through the rendezvous TCPStore) carries the requests; the SERVER PROCESS
holds table state as host numpy arrays (sparse tables are host-memory
objects in the reference too — device meshes are the collective path, the
PS path is explicitly the host-side one).

Role separation is real: ``fleet.init(role)`` on a SERVER process serves
these tables; WORKER processes never hold them — ``push_sparse`` ships
(rows, values) across the process boundary and ``pull_sparse`` reads the
server's current state (including its staleness under geo batching, which
is the semantics the Communicator contract promises).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .. import observability as _obs
from .. import resilience as _resil
from ..resilience import faults as _faults

__all__ = ["PsClient", "PushSparseError", "serve_stats",
           "reset_server_state", "SparseTable"]

_log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# accessor rules (round 5 — upstream paddle/fluid/distributed/ps/table/
# accessors: the per-table/per-slot optimizer rule applied ON the server)
# ---------------------------------------------------------------------------


class _RuleBase:
    state_keys = ()

    def ensure_state(self, state, dim):
        """Create this rule's missing state keys on demand: a row's rule
        binds at APPLY time (slot overrides can differ from the slot a row
        was first materialized/pulled under), so state cannot be fixed at
        materialization."""
        for k in self.state_keys:
            if k not in state:
                state[k] = np.zeros(1 if k == "t" else dim, np.float32)


class _SgdRule(_RuleBase):
    state_keys = ()

    def apply(self, value, state, grad, lr):
        value -= lr * grad


class _AdagradRule(_RuleBase):
    state_keys = ("g2",)

    def __init__(self, epsilon=1e-8):
        self.epsilon = float(epsilon)

    def apply(self, value, state, grad, lr):
        self.ensure_state(state, grad.shape[-1])
        state["g2"] += grad * grad
        value -= lr * grad / (np.sqrt(state["g2"]) + self.epsilon)


class _AdamRule(_RuleBase):
    state_keys = ("m", "v", "t")

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.beta1, self.beta2 = float(beta1), float(beta2)
        self.epsilon = float(epsilon)

    def apply(self, value, state, grad, lr):
        self.ensure_state(state, grad.shape[-1])
        state["t"] += 1.0
        t = state["t"][0] if state["t"].ndim else state["t"]
        state["m"][:] = self.beta1 * state["m"] + (1 - self.beta1) * grad
        state["v"][:] = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        mhat = state["m"] / (1 - self.beta1 ** t)
        vhat = state["v"] / (1 - self.beta2 ** t)
        value -= lr * mhat / (np.sqrt(vhat) + self.epsilon)


_RULES = {"sgd": _SgdRule, "adagrad": _AdagradRule, "adam": _AdamRule}


def _make_rule(spec: Union[str, Dict[str, Any]]):
    if isinstance(spec, str):
        return _RULES[spec]()
    spec = dict(spec)
    return _RULES[spec.pop("name")](**spec)


class SparseTable:
    """Hash-map sparse table with a per-table accessor rule, per-SLOT
    overrides, frequency/recency metadata, TTL eviction and
    snapshot/restore (upstream: ps/table/ MemorySparseTable + the
    accessor's slot-parameterized SGD rules + shrink()/save()/load()).

    Rows materialize on first touch via the initializer; each row carries
    its accessor state (adagrad g2 / adam m,v,t), a show counter and the
    last-seen logical tick (one tick per push batch). ``slot_params`` maps
    a feature's SLOT id to overrides — currently ``lr`` and ``rule`` —
    which is how CTR models give embeddings per-field learning rates."""

    def __init__(self, dim: int, dtype: str = "float32",
                 accessor: Union[str, Dict[str, Any]] = "sgd",
                 lr: float = 0.01, initializer: str = "zeros",
                 init_scale: float = 0.01, seed: int = 0,
                 slot_params: Optional[Dict[int, Dict[str, Any]]] = None):
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.lr = float(lr)
        self.rule = _make_rule(accessor)
        self.initializer = initializer
        self.init_scale = float(init_scale)
        self._rng = np.random.default_rng(seed)
        self.slot_params = {int(k): dict(v)
                            for k, v in (slot_params or {}).items()}
        self._slot_rules = {s: _make_rule(p["rule"])
                            for s, p in self.slot_params.items()
                            if "rule" in p}
        self.values: Dict[int, np.ndarray] = {}
        self.state: Dict[int, Dict[str, np.ndarray]] = {}
        self.show: Dict[int, int] = {}
        self.last_seen: Dict[int, int] = {}
        self.tick = 0

    def _rule_for(self, slot: int):
        return self._slot_rules.get(slot, self.rule)

    def _lr_for(self, slot: int) -> float:
        return float(self.slot_params.get(slot, {}).get("lr", self.lr))

    def _materialize(self, fid: int, slot: int = -1):
        if fid not in self.values:
            if self.initializer == "uniform":
                row = self._rng.uniform(-self.init_scale, self.init_scale,
                                        self.dim).astype(self.dtype)
            else:
                row = np.zeros(self.dim, self.dtype)
            self.values[fid] = row
            self.state[fid] = {}  # rule state materializes at apply time
            self.show[fid] = 0
            self.last_seen[fid] = self.tick
        return self.values[fid]

    def pull(self, ids: np.ndarray, slots: Optional[np.ndarray] = None):
        out = np.empty((len(ids), self.dim), self.dtype)
        for i, fid in enumerate(ids):
            fid = int(fid)
            slot = int(slots[i]) if slots is not None else -1
            out[i] = self._materialize(fid, slot)
            self.show[fid] += 1
            self.last_seen[fid] = self.tick
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray,
             slots: Optional[np.ndarray] = None, lr: Optional[float] = None):
        self.tick += 1
        for i, fid in enumerate(ids):
            fid = int(fid)
            slot = int(slots[i]) if slots is not None else -1
            value = self._materialize(fid, slot)
            eff_lr = float(lr) if lr is not None else self._lr_for(slot)
            self._rule_for(slot).apply(value, self.state[fid],
                                       grads[i].astype(np.float32), eff_lr)
            self.last_seen[fid] = self.tick

    def shrink(self, max_unseen: Optional[int] = None,
               min_show: Optional[int] = None) -> int:
        """Evict rows not seen for ``max_unseen`` ticks or with fewer than
        ``min_show`` accesses (upstream table->shrink() with the
        accessor's delete thresholds). Returns the eviction count."""
        drop = [fid for fid in self.values
                if (max_unseen is not None
                    and self.tick - self.last_seen[fid] > max_unseen)
                or (min_show is not None and self.show[fid] < min_show)]
        for fid in drop:
            for d in (self.values, self.state, self.show, self.last_seen):
                d.pop(fid, None)
        return len(drop)

    # -- snapshot / restore --------------------------------------------------
    def save(self, path: str) -> None:
        ids = np.array(sorted(self.values), np.int64)
        vals = np.stack([self.values[i] for i in ids]) if len(ids) \
            else np.zeros((0, self.dim), self.dtype)
        # UNION of state keys across rows (slot-rule overrides mean rows
        # carry different keys); absent keys round-trip as zeros, which is
        # exactly the lazily-created initial state
        all_keys = sorted({k for st in self.state.values() for k in st})
        state_blob = {}
        for k in all_keys:
            shape = next(st[k].shape for st in self.state.values()
                         if k in st)
            state_blob[f"state_{k}"] = np.stack(
                [self.state[i].get(k, np.zeros(shape, np.float32))
                 for i in ids]) if len(ids) else np.zeros((0,) + shape)
        np.savez(path, ids=ids, values=vals,
                 show=np.array([self.show[i] for i in ids], np.int64),
                 last_seen=np.array([self.last_seen[i] for i in ids],
                                    np.int64),
                 tick=np.int64(self.tick), **state_blob)

    def load(self, path: str) -> None:
        z = np.load(path)
        keys = [f[len("state_"):] for f in z.files if f.startswith("state_")]
        self.values.clear(); self.state.clear()
        self.show.clear(); self.last_seen.clear()
        self.tick = int(z["tick"])
        for i, fid in enumerate(z["ids"]):
            fid = int(fid)
            self.values[fid] = z["values"][i].astype(self.dtype)
            self.state[fid] = {k: z[f"state_{k}"][i].astype(np.float32)
                               for k in keys}
            self.show[fid] = int(z["show"][i])
            self.last_seen[fid] = int(z["last_seen"][i])


class PushSparseError(RuntimeError):
    """A logical ``push_sparse`` failed at one shard after EARLIER shards
    may already have applied their slice (ADVICE r5's partial-failure
    window). Carries the logical push's ``seq``: retry with
    ``push_sparse(..., seq=err.seq)`` and the shards that already applied
    recognize the duplicate server-side (their per-shard dedup stream saw
    this seq) while the failed shard applies it for the first time — the
    retry is idempotent instead of double-applying.

    Retry BEFORE issuing further pushes from this client: a later push
    advances every shard's watermark past ``seq`` and the retry would be
    discarded as a duplicate (a silent drop)."""

    def __init__(self, message: str, seq: int, failed_shard: int):
        super().__init__(message)
        self.seq = seq
        self.failed_shard = failed_shard


# ---------------------------------------------------------------------------
# server-side state (lives in the PS SERVER process; reached via rpc)
# ---------------------------------------------------------------------------

_TABLES: Dict[str, np.ndarray] = {}
_SPARSE: Dict[str, SparseTable] = {}
_SPARSE_CFG: Dict[str, Dict[str, Any]] = {}
# at-most-once guard for retried pushes: last applied sequence id per
# client (a retry after a lost REPLY must not re-apply the gradient).
# Reset on server restart — a snapshot restore rewinds past any in-flight
# push anyway, so exactly-once holds within a server incarnation.
_PUSH_SEQ: Dict[str, int] = {}
_LOCK = threading.Lock()
_STATS = {"pushes": 0, "pulls": 0, "creates": 0, "evictions": 0,
          "dup_pushes": 0, "load_skipped": 0}


def reset_server_state() -> None:
    with _LOCK:
        _TABLES.clear()
        _SPARSE.clear()
        _SPARSE_CFG.clear()
        _PUSH_SEQ.clear()
        _STATS.update(pushes=0, pulls=0, creates=0, evictions=0,
                      dup_pushes=0, load_skipped=0)


def _srv_create(name: str, value_bytes: bytes, shape: Tuple[int, ...],
                dtype: str) -> bool:
    """Install a table (idempotent: the first creator wins, matching the
    reference's load-once table shards)."""
    with _LOCK:
        if name not in _TABLES:
            _TABLES[name] = np.frombuffer(value_bytes, dtype=dtype) \
                .reshape(shape).copy()
            _STATS["creates"] += 1
    return True


def _seq_is_dup_locked(client_key: Optional[str], seq: Optional[int]) -> bool:
    """True when (client, seq) was already applied (caller holds _LOCK)."""
    if client_key is None or seq is None:
        return False
    if _PUSH_SEQ.get(client_key, -1) >= seq:
        _STATS["dup_pushes"] += 1
        return True
    _PUSH_SEQ[client_key] = seq
    return False


def _srv_push(name: str, ids_bytes: bytes, grad_bytes: bytes,
              n: int, dim: int, lr: float,
              client_key: Optional[str] = None,
              seq: Optional[int] = None) -> bool:
    """Apply an SGD scatter-update: table[ids] -= lr * grad. Duplicate ids
    accumulate (segment-sum semantics, the reference accessor's rule)."""
    # before the dedup/apply critical section: an injected handler fault
    # models a server that died BEFORE applying (the client may retry)
    _faults.fault_point("ps.handler")
    with _LOCK:
        if _seq_is_dup_locked(client_key, seq):
            return True
        t = _TABLES[name]
        ids = np.frombuffer(ids_bytes, dtype=np.int64)
        g = np.frombuffer(grad_bytes, dtype=np.float32).reshape(n, dim)
        np.subtract.at(t, ids, lr * g.astype(t.dtype))
        _STATS["pushes"] += 1
    return True


def _srv_pull(name: str, ids_bytes: bytes) -> bytes:
    with _LOCK:
        t = _TABLES[name]
        ids = np.frombuffer(ids_bytes, dtype=np.int64)
        _STATS["pulls"] += 1
        return t[ids].tobytes()


def _srv_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_STATS)


def _srv_table_snapshot(name: str) -> Tuple[bytes, Tuple[int, ...], str]:
    """Test/introspection surface: the server's CURRENT table state —
    exactly what geo-staleness assertions need to observe."""
    with _LOCK:
        t = _TABLES[name]
        return t.tobytes(), t.shape, str(t.dtype)


def serve_stats() -> Dict[str, int]:
    """Server-local stats read (same process)."""
    return _srv_stats()


# -- hash sparse-table endpoints (round 5) -----------------------------------

def _srv_create_sparse(name: str, cfg: Dict[str, Any]) -> bool:
    with _LOCK:
        if name not in _SPARSE:
            _SPARSE[name] = SparseTable(**cfg)
            _SPARSE_CFG[name] = dict(cfg)
            _STATS["creates"] += 1
    return True


def _srv_push_sparse(name: str, ids_bytes: bytes, grad_bytes: bytes, n: int,
                     slots_bytes: Optional[bytes],
                     lr: Optional[float],
                     client_key: Optional[str] = None,
                     seq: Optional[int] = None) -> bool:
    _faults.fault_point("ps.handler")
    with _LOCK:
        if _seq_is_dup_locked(client_key, seq):
            return True
        t = _SPARSE[name]
        ids = np.frombuffer(ids_bytes, np.int64)
        g = np.frombuffer(grad_bytes, np.float32).reshape(n, t.dim)
        slots = np.frombuffer(slots_bytes, np.int64) \
            if slots_bytes is not None else None
        t.push(ids, g, slots, lr)
        _STATS["pushes"] += 1
    return True


def _srv_pull_sparse(name: str, ids_bytes: bytes,
                     slots_bytes: Optional[bytes]) -> bytes:
    with _LOCK:
        t = _SPARSE[name]
        ids = np.frombuffer(ids_bytes, np.int64)
        slots = np.frombuffer(slots_bytes, np.int64) \
            if slots_bytes is not None else None
        _STATS["pulls"] += 1
        return t.pull(ids, slots).tobytes()


def _srv_shrink(name: str, max_unseen: Optional[int],
                min_show: Optional[int]) -> int:
    with _LOCK:
        n = _SPARSE[name].shrink(max_unseen, min_show)
        _STATS["evictions"] += n
        return n


def _srv_sparse_rows(name: str) -> int:
    with _LOCK:
        return len(_SPARSE[name].values)


def _srv_save(dirname: str) -> List[str]:
    """Snapshot every table (dense + sparse + sparse configs) to
    ``dirname`` — the recovery image a respawned server loads."""
    os.makedirs(dirname, exist_ok=True)
    saved = []
    with _LOCK:
        for name, t in _TABLES.items():
            np.save(os.path.join(dirname, f"dense_{name}.npy"), t)
            saved.append(name)
        for name, t in _SPARSE.items():
            t.save(os.path.join(dirname, f"sparse_{name}.npz"))
            saved.append(name)
        if _SPARSE_CFG:
            import json
            with open(os.path.join(dirname, "sparse_cfg.json"), "w") as f:
                json.dump(_SPARSE_CFG, f)
    return saved


def _srv_load(dirname: str) -> List[str]:
    """Restore a `_srv_save` snapshot (server-restart recovery).

    A sparse ``.npz`` with no matching entry in ``sparse_cfg.json`` (file
    missing, or table absent from it) is SKIPPED with a loud error — it
    used to be restored with a guessed ``{"dim": 1}`` config, so the
    wrong dim/accessor/lr only surfaced later as an opaque numpy
    broadcast error on the first pull (ADVICE r5). The failure now
    surfaces at load, where the operator can still fix the snapshot."""
    import json
    loaded = []
    with _LOCK:
        cfg_path = os.path.join(dirname, "sparse_cfg.json")
        cfgs = {}
        have_cfg_file = os.path.exists(cfg_path)
        if have_cfg_file:
            with open(cfg_path) as f:
                cfgs = json.load(f)
        for fn in sorted(os.listdir(dirname)):
            path = os.path.join(dirname, fn)
            if fn.startswith("dense_") and fn.endswith(".npy"):
                name = fn[len("dense_"):-len(".npy")]
                _TABLES[name] = np.load(path)
                loaded.append(name)
            elif fn.startswith("sparse_") and fn.endswith(".npz"):
                name = fn[len("sparse_"):-len(".npz")]
                if name not in cfgs:
                    _STATS["load_skipped"] = \
                        _STATS.get("load_skipped", 0) + 1
                    _log.error(
                        "ps: snapshot %s has no entry for table %r in "
                        "sparse_cfg.json (%s) — SKIPPING the table "
                        "instead of guessing its dim/accessor/lr; "
                        "restore the config file (or re-snapshot with "
                        "_srv_save) and reload",
                        dirname, name,
                        "file missing" if not have_cfg_file
                        else "table absent")
                    continue
                cfg = dict(cfgs[name])
                # json stringifies the slot keys; restore int slots
                if "slot_params" in cfg:
                    cfg["slot_params"] = {int(k): v for k, v in
                                          cfg["slot_params"].items()}
                t = SparseTable(**cfg)
                t.load(path)
                _SPARSE[name] = t
                _SPARSE_CFG[name] = cfg
                loaded.append(name)
    return loaded


# ---------------------------------------------------------------------------
# worker-side client
# ---------------------------------------------------------------------------

class PsClient:
    """push/pull against tables living in PS SERVER process(es).

    The analogue of the reference BrpcPsClient: every method is a remote
    call; nothing is cached worker-side (pulls observe the server's real,
    possibly-stale-under-geo state). Round 5:

    * MULTI-SERVER sharding — pass a list of server names and sparse rows
      shard by ``id % num_servers`` (upstream shards tables across
      PServers the same way); dense tables live whole on server 0.
    * FAILOVER — a connection failure re-resolves the server's endpoint
      from the rendezvous store and retries with backoff for up to
      ``retry_timeout`` seconds, so a killed-and-respawned server (which
      re-registers under the same name) is transparent to workers."""

    def __init__(self, server: Union[str, List[str]], lr: float = 0.01,
                 retry_timeout: float = 60.0,
                 max_pending_async: int = 256):
        import uuid
        self.servers = [server] if isinstance(server, str) else list(server)
        self.server = self.servers[0]  # dense/back-compat target
        self.lr = float(lr)
        self.retry_timeout = float(retry_timeout)
        # cap on queued-but-unsent async pushes: a down server must not
        # grow an unbounded buffer of gradient blobs (oldest are dropped,
        # counted, and logged once the cap is hit)
        self.max_pending_async = int(max_pending_async)
        # per-client push sequencing: a retried push the server already
        # applied (lost reply) is recognized and skipped server-side
        self._client_key = uuid.uuid4().hex
        self._seq = 0
        self._seq_lock = threading.Lock()
        # serializes LOGICAL sparse pushes: one seq covers every shard of
        # a push, and the per-shard dedup watermarks are monotonic — if a
        # second push could interleave between one push's shard sends,
        # the first push's later-shard slices would arrive below the
        # advanced watermark and be discarded as duplicates (silent
        # gradient loss). Lock order: _push_lock, then _seq_lock inside
        # (never the reverse).
        self._push_lock = threading.Lock()
        self._async_pool = None  # lazy single-thread executor for wait=False
        self._async_gen = 0  # bumps per drain-thread generation (see below)
        self._async_drop_throttle = _obs.LogThrottle()

    def _rpc(self):
        from . import rpc
        return rpc

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _call(self, server: str, fn, args):
        """rpc_sync with endpoint re-resolution + backoff on TRANSPORT
        failure only — a server-side exception (shipped back with its
        original type) means the call executed and must not be retried.

        The retry schedule is the named ``ps.rpc`` :class:`RetryPolicy`
        (jittered 0.2s→2.0s backoff; override via
        ``PADDLE_TPU_RETRY_PS_RPC_*``) under a ``deadline_scope`` of
        ``retry_timeout`` seconds, so the rpc layer's own dial retries
        clamp to the same monotonic instant instead of compounding. A
        per-server :class:`CircuitBreaker` turns a dead shard into fast
        :class:`BreakerOpen` failures between probes — the loop treats
        those exactly like transport failures (keep backing off until the
        deadline), so failover semantics are unchanged."""
        import time as _time
        rpc = self._rpc()
        policy = _resil.get_policy("ps.rpc", base_delay=0.2, multiplier=1.6,
                                   max_delay=2.0, jitter=0.25)
        breaker = _resil.breaker_for(f"ps/{server}")
        _obs.inc("ps.rpc_calls_total")
        last_transport_err: Optional[BaseException] = None
        with _resil.deadline_scope(self.retry_timeout):
            for attempt in policy.start():
                try:
                    breaker.before_call()
                    try:
                        # the injected-fault seam sits INSIDE the
                        # record_success/record_failure try: an injected
                        # non-transport fault used to escape between
                        # before_call() and this try with the half-open
                        # probe still out, wedging the breaker half-open
                        # forever (found by the resource-discipline lint)
                        _faults.fault_point("ps.call")
                        # only SUCCESSFUL attempts land in the latency
                        # histogram — timing failed attempts would fill
                        # ps.rpc_seconds with connect-timeout durations
                        # and break count parity with ps.rpc_calls_total
                        if _obs.enabled():
                            t0 = _time.perf_counter()
                            result = rpc.rpc_sync(server, fn, args=args)
                            _faults.fault_point("ps.reply")
                            _obs.observe("ps.rpc_seconds",
                                         _time.perf_counter() - t0)
                        else:
                            result = rpc.rpc_sync(server, fn, args=args)
                            _faults.fault_point("ps.reply")
                    except rpc.RpcTransportError:
                        raise
                    except BaseException:
                        # server-side exception shipped back with its
                        # original type: the endpoint EXECUTED the call —
                        # healthy. Recording success here also frees a
                        # half-open probe slot; without it, a probe that
                        # hit an application error would wedge the
                        # breaker half-open forever.
                        breaker.record_success()
                        raise
                    breaker.record_success()
                    return result
                except rpc.RpcTransportError as e:
                    # separate arms (not one `except (Transport, Open)`
                    # with an isinstance dispatch) so record_failure is
                    # unconditional here — a held half-open probe is
                    # returned on EVERY path out of this handler
                    breaker.record_failure()
                    last_transport_err = e
                    self._retry_backoff(rpc, server, breaker, attempt, e)
                except _resil.BreakerOpen as e:
                    # before_call short-circuited: no probe was taken,
                    # nothing to record — keep backing off until the
                    # deadline, exactly like a transport failure
                    self._retry_backoff(rpc, server, breaker, attempt,
                                        last_transport_err or e)

    def _retry_backoff(self, rpc, server: str, breaker, attempt,
                       err: BaseException) -> None:
        """One failed ``_call`` attempt's bookkeeping: backoff-sleep (or
        re-raise on a spent budget), then endpoint re-resolution."""
        try:
            # backoff-sleeps, or re-raises on a spent budget; exhaustion
            # surfaces the last REAL transport error (callers pin on
            # RpcTransportError), never a BreakerOpen short-circuit
            attempt.fail(err)
        except _resil.BreakerOpen as bo:
            # budget spent while this call only ever saw the breaker
            # (opened by PREVIOUS calls): surface the documented
            # transport type, not a third one
            _obs.inc("ps.rpc_failures_total")
            raise rpc.RpcTransportError(
                f"rpc to {server} failed: retry budget spent "
                f"while circuit breaker open") from bo
        except BaseException:
            _obs.inc("ps.rpc_failures_total")
            raise
        _obs.inc("ps.rpc_retries_total")
        try:
            old = rpc.get_worker_info(server)
            fresh = rpc.refresh_worker_info(server)
            # a FAILOVER is an endpoint change (respawned server
            # re-registered); a same-endpoint refresh is just a retry
            # and must not inflate the failover count
            if (fresh.ip, fresh.port) != (old.ip, old.port):
                _obs.inc("ps.rpc_failovers_total")
                # new address: the old failure run says nothing about
                # it — close the breaker so the respawned server is
                # probed immediately
                breaker.reset()
        except Exception:
            pass  # store briefly unreachable: keep backing off

    def create_table(self, name: str, value) -> None:
        arr = np.asarray(value)
        self._call(self.server, _srv_create,
                   (name, arr.tobytes(), arr.shape, str(arr.dtype)))

    def _submit_async(self, server: str, fn, args):
        """Async path THROUGH the retrying ``_call`` wrapper (ADVICE r5:
        ``rpc_async`` bypassed failover, so a transport failure silently
        dropped the gradient). The returned future still resolves to the
        call result; a push that exhausts its retry budget is logged AND
        counted (``ps.dropped_async_pushes_total``) before the exception is
        parked on the future — visible even to callers that never wait.

        Pushes drain on ONE daemon thread; the stream's seq is assigned and
        the item enqueued under ONE lock hold, so enqueue order == seq
        order == apply order even with concurrent pushers (a lower seq
        arriving after a higher one would be discarded by the server's
        dedup watermark as a "duplicate"). A retry loop still backing off
        at interpreter exit cannot block shutdown the way a joined
        ThreadPoolExecutor worker would, and the thread holds only a WEAK
        reference to the client between items so an abandoned client is
        still collectible (its __del__ shuts the thread down)."""
        import queue as _queue
        import weakref
        from concurrent.futures import Future
        from .rpc import FutureWrapper

        with self._seq_lock:
            if self._async_pool is None:
                q: "_queue.Queue" = _queue.Queue()
                wself = weakref.ref(self)

                def drain():
                    while True:
                        item = q.get()
                        if item is None:
                            return
                        fut, srv, f, a = item
                        client = wself()
                        if client is None:
                            # owner collected mid-queue: stop draining;
                            # the unapplied push still counts as dropped
                            if fut.cancel():
                                _obs.inc("ps.dropped_async_pushes_total")
                            return
                        if not fut.set_running_or_notify_cancel():
                            del client
                            continue
                        try:
                            fut.set_result(client._call(srv, f, a))
                        except Exception as e:
                            _obs.inc("ps.dropped_async_pushes_total")
                            _log.error(
                                "ps: async push to %s dropped after "
                                "retries (%s: %s)", srv,
                                type(e).__name__, e)
                            fut.set_exception(e)
                        del client  # hold no strong ref while idle

                # each drain-thread GENERATION dedups on its own key
                # stream: after a timed-out close() an old thread may
                # still be mid-retry, and if a recreated pool shared its
                # stream, the new thread's pushes would advance the
                # server watermark past the old retry — which would then
                # be discarded as a "duplicate" (a silent drop)
                self._async_gen += 1
                t = threading.Thread(
                    target=drain, daemon=True,
                    name=f"ps-async-{self._client_key[:8]}")
                t.start()
                self._async_pool = (q, t)

            q2 = self._async_pool[0]
            # bounded buffer: drop the OLDEST queued push once the cap is
            # hit — recency wins for gradients, memory stays bounded, and
            # the drop is counted + logged like every other drop
            dropped = 0
            while q2.qsize() >= self.max_pending_async:
                try:
                    old = q2.get_nowait()
                except Exception:  # Empty: drain thread got there first
                    break
                if old is not None and old[0].cancel():
                    dropped += 1
            if dropped:
                _obs.inc("ps.dropped_async_pushes_total", dropped)
                # rate-limited: at cap this fires on every push; the
                # counter carries the magnitude
                if self._async_drop_throttle.ready():
                    _log.error(
                        "ps: async push queue full (cap %d); dropping "
                        "oldest queued push(es)", self.max_pending_async)
            fut: Future = Future()
            self._seq += 1
            q2.put((fut, server, fn,
                    args + (f"{self._client_key}/async{self._async_gen}",
                            self._seq)))
        return FutureWrapper(fut)

    def close(self, wait: bool = True, timeout: Optional[float] = None
              ) -> None:
        """Stop the async-push drain thread (queued-but-unstarted pushes
        are cancelled). ``wait`` joins the thread so a push currently in
        its retry loop gets up to ``timeout`` seconds to finish."""
        with self._seq_lock:
            pool, self._async_pool = self._async_pool, None
        if pool is None:
            return
        q, t = pool
        cancelled = 0
        try:
            while True:
                item = q.get_nowait()
                if item is not None and item[0].cancel():
                    cancelled += 1
        except Exception:
            pass  # queue drained (Empty): nothing left to cancel
        if cancelled:
            # the dropped-push contract covers cancellation too: a queued
            # gradient discarded by close() must never vanish silently
            _obs.inc("ps.dropped_async_pushes_total", cancelled)
            _log.error("ps: close() cancelled %d queued async push(es); "
                       "those gradients were not applied", cancelled)
        q.put(None)
        if wait:
            t.join(timeout)

    def __del__(self):
        try:
            self.close(wait=False)
        except Exception:
            pass  # interpreter teardown: drain thread is daemon anyway

    def push(self, name: str, ids, grad, wait: bool = True):
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grad, np.float32).reshape(ids.shape[0], -1)
        args = (name, ids.tobytes(), g.tobytes(), g.shape[0], g.shape[1],
                self.lr)
        if wait:
            # sync stream: caller-ordered, keyed on the plain client key
            return self._call(self.server, _srv_push,
                              args + (self._client_key, self._next_seq()))
        # async pushes dedup on their OWN key stream (appended with their
        # seq inside _submit_async, atomically with the enqueue): with a
        # shared stream, a sync push overtaking an async retry during its
        # backoff window would advance the server's seq watermark past the
        # retry, and the server would then discard the retried gradient as
        # a "duplicate" — a silent drop reported as success.
        return self._submit_async(self.server, _srv_push, args)

    def pull(self, name: str, ids, dim: int, dtype=np.float32) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        raw = self._call(self.server, _srv_pull, (name, ids.tobytes()))
        return np.frombuffer(raw, dtype=dtype).reshape(ids.shape[0], dim)

    def table_snapshot(self, name: str) -> np.ndarray:
        raw, shape, dtype = self._call(self.server, _srv_table_snapshot,
                                       (name,))
        return np.frombuffer(raw, dtype=dtype).reshape(shape)

    def stats(self) -> Dict[str, int]:
        """Aggregated counters across every server shard."""
        total: Dict[str, int] = {}
        for srv in self.servers:
            for k, v in self._call(srv, _srv_stats, ()).items():
                total[k] = total.get(k, 0) + v
        return total

    # -- hash sparse tables (sharded across servers) -------------------------
    def _shard(self, ids: np.ndarray):
        return ids % len(self.servers)

    def create_sparse_table(self, name: str, dim: int, **cfg) -> None:
        cfg = dict(cfg, dim=int(dim))
        for srv in self.servers:
            self._call(srv, _srv_create_sparse, (name, cfg))

    def push_sparse(self, name: str, ids, grad, slots=None, lr=None,
                    seq: Optional[int] = None) -> int:
        """Shard-and-push one logical gradient batch; returns the logical
        push's ``seq``.

        ONE seq is drawn per LOGICAL push and reused for every shard
        (ADVICE r5): each shard dedups on its own key stream
        (``<client>/<shard>``), so when shard k fails after shards < k
        applied, retrying the whole call with ``seq=err.seq`` (from the
        raised :class:`PushSparseError`) re-sends the same seq everywhere
        — applied shards skip it as a duplicate, the failed shard applies
        it. Before this, each shard drew a fresh seq, so an application-
        level retry after a partial failure double-applied the
        already-applied shard slices.

        Logical pushes are SERIALIZED per client (``_push_lock``): with
        one seq spanning several shard sends, a second push interleaving
        between them would advance the per-shard watermarks past the
        first push's still-unsent slices, and the server would discard
        those as duplicates — silent gradient loss reported as success."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = np.asarray(grad, np.float32).reshape(ids.shape[0], -1)
        slots = None if slots is None else \
            np.asarray(slots, np.int64).reshape(-1)
        rpc = self._rpc()
        with self._push_lock:
            if seq is None:
                seq = self._next_seq()
            shard = self._shard(ids)
            for s, srv in enumerate(self.servers):
                m = shard == s
                if not m.any():
                    continue
                try:
                    self._call(srv, _srv_push_sparse,
                               (name, ids[m].tobytes(), g[m].tobytes(),
                                int(m.sum()),
                                slots[m].tobytes() if slots is not None
                                else None,
                                lr, f"{self._client_key}/{s}", seq))
                except rpc.RpcTransportError as exc:
                    # only TRANSPORT exhaustion gets the retry-with-seq
                    # wrapper: a server-side exception (shipped back with
                    # its original type) means the shard EXECUTED the
                    # call — a deterministic application error, where
                    # "retry the same seq" is wrong advice — so it
                    # propagates unchanged
                    _obs.inc("ps.partial_pushes_total")
                    raise PushSparseError(
                        f"push_sparse({name!r}) seq {seq} failed at "
                        f"shard {s} ({srv}); earlier shards may have "
                        f"applied — retry with push_sparse(..., "
                        f"seq={seq}) BEFORE any other push so applied "
                        f"shards dedup ({type(exc).__name__}: {exc})",
                        seq, s) from exc
        return seq

    def pull_sparse(self, name: str, ids, dim: int, slots=None,
                    dtype=np.float32) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        slots = None if slots is None else \
            np.asarray(slots, np.int64).reshape(-1)
        dtype = np.dtype(dtype)
        out = np.empty((ids.shape[0], dim), dtype)
        shard = self._shard(ids)
        for s, srv in enumerate(self.servers):
            m = shard == s
            if not m.any():
                continue
            raw = self._call(srv, _srv_pull_sparse,
                             (name, ids[m].tobytes(),
                              slots[m].tobytes() if slots is not None
                              else None))
            out[m] = np.frombuffer(raw, dtype).reshape(-1, dim)
        return out

    def shrink(self, name: str, max_unseen: Optional[int] = None,
               min_show: Optional[int] = None) -> int:
        return sum(self._call(srv, _srv_shrink,
                              (name, max_unseen, min_show))
                   for srv in self.servers)

    def sparse_rows(self, name: str) -> int:
        return sum(self._call(srv, _srv_sparse_rows, (name,))
                   for srv in self.servers)

    def save(self, dirname: str) -> None:
        """Each server snapshots its shard into ``dirname/shard_<i>``."""
        for i, srv in enumerate(self.servers):
            self._call(srv, _srv_save, (os.path.join(dirname, f"shard_{i}"),))

    def load(self, dirname: str, server_index: Optional[int] = None) -> None:
        """Restore snapshots — all servers, or just the respawned one."""
        for i, srv in enumerate(self.servers):
            if server_index is not None and i != server_index:
                continue
            self._call(srv, _srv_load, (os.path.join(dirname, f"shard_{i}"),))
