"""launch entrypoint: python -m paddle_tpu.distributed.launch [...] train.py"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--devices", "--gpus", "--xpus", dest="devices", default=None,
                   help="visible accelerator ids (informational on TPU SPMD)")
    p.add_argument("--nnodes", default="1", help="number of hosts (or range)")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="worker processes per host; TPU default is 1 (SPMD)")
    p.add_argument("--master", default=None, help="coordinator host:port")
    p.add_argument("--rank", type=int, default=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default="default")
    p.add_argument("--run_mode", default="collective",
                   help="collective | ps")
    p.add_argument("--servers", default="", help="ps mode: ip:port list")
    p.add_argument("--trainers", default="", help="ps mode: ip:port list")
    p.add_argument("--server_num", type=int, default=0)
    p.add_argument("--trainer_num", type=int, default=0)
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0 off; 1 restart-on-fault (same world size); "
                        "2 resize on membership loss (single- AND "
                        "multi-node; see --elastic_master)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--elastic_timeout", type=float, default=30.0,
                   help="heartbeat staleness that counts as a hang (s)")
    p.add_argument("--elastic_master", default=None,
                   help="multi-node elastic: host:port of the SHARED job "
                        "store (host it outside the trainer nodes — the "
                        "etcd analogue — so any node may die); node 0 "
                        "hosts one when omitted")
    p.add_argument("--node_timeout", type=float, default=10.0,
                   help="multi-node elastic: node-lease staleness that "
                        "counts a whole node as lost (s)")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _launch_ps(args) -> int:
    """PS-mode controller (parity: launch/controllers/ps.py): spawn server
    processes (TRAINING_ROLE=PSERVER) and trainer processes on localhost."""
    import socket

    def _free_ports(n: int):
        socks, ports = [], []
        for _ in range(n):  # hold all sockets until every port is picked so
            s = socket.socket()  # the OS can't hand the same one out twice
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    os.makedirs(args.log_dir, exist_ok=True)
    servers = [e for e in args.servers.split(",") if e] or [
        f"127.0.0.1:{p}" for p in _free_ports(args.server_num or 1)]
    trainers = [e for e in args.trainers.split(",") if e] or [
        f"127.0.0.1:{p}" for p in _free_ports(args.trainer_num or 1)]
    cmd = [sys.executable, args.script] + list(args.script_args)
    common = {
        "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(servers),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(trainers),
        "PADDLE_TRAINERS_NUM": str(len(trainers)),
    }
    procs: List[subprocess.Popen] = []
    for i, ep in enumerate(servers):
        env = dict(os.environ, TRAINING_ROLE="PSERVER",
                   PADDLE_CURRENT_ENDPOINT=ep, **common)
        logf = open(os.path.join(args.log_dir, f"serverlog.{i}"), "w")
        procs.append(subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf))
    worker_procs: List[subprocess.Popen] = []
    for i, ep in enumerate(trainers):
        env = dict(os.environ, TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(i), PADDLE_CURRENT_ENDPOINT=ep,
                   **common)
        logf = open(os.path.join(args.log_dir, f"workerlog.{i}"), "w")
        worker_procs.append(subprocess.Popen(cmd, env=env, stdout=logf,
                                             stderr=logf))
    code = 0
    for pr in worker_procs:
        code = pr.wait() or code
    for pr in procs:  # servers exit once workers signal stop_worker
        try:
            code = pr.wait(timeout=60) or code
        except subprocess.TimeoutExpired:
            pr.terminate()
            code = code or 1
    return code


def launch_main() -> int:
    args = _parse()
    if args.run_mode == "ps" or args.servers or args.server_num:
        return _launch_ps(args)
    nnodes = int(str(args.nnodes).split(":")[0])
    nproc = args.nproc_per_node or 1
    os.makedirs(args.log_dir, exist_ok=True)

    base_port = 37777
    master = args.master or f"127.0.0.1:{base_port}"
    world = nnodes * nproc
    endpoints = ",".join(
        f"127.0.0.1:{base_port + i}" for i in range(world)) if nnodes == 1 \
        else os.environ.get("PADDLE_TRAINER_ENDPOINTS", master)

    manager = None
    agent = None
    if args.elastic_level > 0 and nnodes > 1:
        # round 5: per-node agents coordinating through a SHARED job store
        # (supervisor = lowest live node) — level-2 resize works across
        # nodes; kill a whole node and the survivors re-form the world
        from ..fleet.elastic import MultiNodeElasticAgent
        from ..store import TCPStore
        if args.elastic_master:
            host, port = args.elastic_master.rsplit(":", 1)
            job_store = TCPStore(host, int(port))
            store_ep = args.elastic_master
        else:
            # default: node 0 hosts the job store BELOW the endpoint port
            # ladder (base_port + i grows upward — sharing a port with a
            # trainer endpoint would break rendezvous)
            mhost, mport = master.rsplit(":", 1)
            store_ep = f"{mhost}:{int(mport) - 2}"
            job_store = TCPStore(mhost, int(mport) - 2,
                                 is_master=(args.rank == 0))
        agent = MultiNodeElasticAgent(
            node_rank=args.rank, nnodes=nnodes, nproc_per_node=nproc,
            store=job_store, elastic_level=args.elastic_level,
            beat_timeout=args.elastic_timeout,
            node_timeout=args.node_timeout,
            max_restarts=args.max_restarts,
            master_endpoint=store_ep)
    elif args.elastic_level > 0:
        from ..fleet.elastic import ElasticManager
        manager = ElasticManager(world_size=world,
                                 elastic_level=args.elastic_level,
                                 beat_timeout=args.elastic_timeout,
                                 max_restarts=args.max_restarts,
                                 rank_offset=args.rank * nproc,
                                 single_node=(nnodes == 1))

    def _spawn_worker(rank, cur_world, cur_endpoints, local_rank,
                      restart_count, extra_env):
        """One worker Popen — shared by the single-node and multi-node
        spawn paths so their env assembly cannot diverge."""
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(cur_world),
            "PADDLE_TRAINER_ENDPOINTS": cur_endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                cur_endpoints.split(",")[rank]
                if rank < len(cur_endpoints.split(",")) else master,
            "PADDLE_MASTER": master,
            "FLAGS_selected_devices": args.devices or "",
        })
        env.update(extra_env)
        suffix = f".{restart_count}" if restart_count else ""
        logf = open(os.path.join(
            args.log_dir, f"workerlog.{local_rank}{suffix}"), "w")
        cmd = [sys.executable, args.script] + list(args.script_args)
        return subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf)

    if agent is not None:
        def spawn_node(epoch: int, node_index: int,
                       topo_nodes: List[int]) -> List[subprocess.Popen]:
            cur_world = len(topo_nodes) * nproc
            # real clusters provide PADDLE_TRAINER_ENDPOINTS (one per
            # ORIGINAL global rank); after a resize the surviving nodes
            # keep THEIR OWN addresses (selected by original node rank),
            # remapped into the new dense rank order. The localhost
            # ladder is the single-host simulation fallback.
            provided = os.environ.get("PADDLE_TRAINER_ENDPOINTS")
            if provided and                     len(provided.split(",")) >= (max(topo_nodes) + 1) * nproc:
                eps = provided.split(",")
                cur_endpoints = ",".join(
                    eps[n * nproc + j]
                    for n in topo_nodes for j in range(nproc))
            else:
                cur_endpoints = ",".join(
                    f"127.0.0.1:{base_port + 100 * epoch + i}"
                    for i in range(cur_world))
            return [
                _spawn_worker(node_index * nproc + lr, cur_world,
                              cur_endpoints, lr, epoch,
                              agent.worker_env())
                for lr in range(nproc)]

        procs = spawn_node(0, agent._my_index(), list(agent.nodes))
        return agent.watch(procs, spawn_node)

    def spawn(restart_count: int = 0) -> List[subprocess.Popen]:
        # elastic level 2 may have RESIZED the world on membership loss:
        # respawn on the manager's current topology with ranks remapped
        # 0..new_world-1 and endpoints re-derived for the new size
        cur_world = manager.world_size if manager is not None else world
        cur_nproc = min(nproc, cur_world) if nnodes == 1 else nproc
        cur_endpoints = ",".join(
            f"127.0.0.1:{base_port + i}" for i in range(cur_world)) \
            if nnodes == 1 else endpoints
        extra = manager.worker_env() if manager is not None else {}
        return [
            _spawn_worker(args.rank * cur_nproc + lr, cur_world,
                          cur_endpoints, lr, restart_count, extra)
            for lr in range(cur_nproc)]

    if world == 1 and manager is None:
        # single worker: run inline so stdout/tty behave normally
        rank_env = {
            "PADDLE_TRAINER_ID": str(args.rank), "PADDLE_TRAINERS_NUM": "1",
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": endpoints.split(",")[0],
            "PADDLE_MASTER": master,
            "FLAGS_selected_devices": args.devices or "",
        }
        os.environ.update(rank_env)
        return subprocess.call(
            [sys.executable, args.script] + list(args.script_args))

    procs = spawn()
    if manager is not None:
        # elastic supervision: restart the pod from checkpoint on fault
        return manager.watch(procs, spawn)
    code = 0
    for pr in procs:
        code = pr.wait() or code
    return code
