"""launch entrypoint: python -m paddle_tpu.distributed.launch [...] train.py"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--devices", "--gpus", "--xpus", dest="devices", default=None,
                   help="visible accelerator ids (informational on TPU SPMD)")
    p.add_argument("--nnodes", default="1", help="number of hosts (or range)")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="worker processes per host; TPU default is 1 (SPMD)")
    p.add_argument("--master", default=None, help="coordinator host:port")
    p.add_argument("--rank", type=int, default=int(os.environ.get("PADDLE_TRAINER_ID", 0)))
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default="default")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--servers", default="")
    p.add_argument("--trainers", default="")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch_main() -> int:
    args = _parse()
    nnodes = int(str(args.nnodes).split(":")[0])
    nproc = args.nproc_per_node or 1
    os.makedirs(args.log_dir, exist_ok=True)

    procs: List[subprocess.Popen] = []
    base_port = 37777
    master = args.master or f"127.0.0.1:{base_port}"
    world = nnodes * nproc
    endpoints = ",".join(
        f"127.0.0.1:{base_port + i}" for i in range(world)) if nnodes == 1 \
        else os.environ.get("PADDLE_TRAINER_ENDPOINTS", master)

    for local_rank in range(nproc):
        rank = args.rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": endpoints.split(",")[rank]
            if rank < len(endpoints.split(",")) else master,
            "PADDLE_MASTER": master,
            "FLAGS_selected_devices": args.devices or "",
        })
        logf = open(os.path.join(args.log_dir, f"workerlog.{local_rank}"), "w")
        cmd = [sys.executable, args.script] + list(args.script_args)
        if world == 1:
            # single worker: run inline so stdout/tty behave normally
            os.environ.update(env)
            return subprocess.call(cmd)
        procs.append(subprocess.Popen(cmd, env=env, stdout=logf, stderr=logf))

    code = 0
    for pr in procs:
        code = pr.wait() or code
    return code
