"""Launcher.

Parity surface: python/paddle/distributed/launch/ (``python -m
paddle.distributed.launch --devices 0,1 train.py`` — per-device worker
processes, rank/endpoint env assignment, log management). TPU-native
process model: ONE worker process per host drives all local chips (SPMD), so
``--devices`` selects visibility rather than forking per device; multi-host
jobs get one process per host with the paddle env contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS) that
``init_parallel_env`` maps onto jax.distributed.
"""

from .main import launch_main  # noqa: F401
