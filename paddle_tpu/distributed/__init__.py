"""``paddle.distributed``: the TPU-native Fleet-capability stack.

Layer map (vs upstream python/paddle/distributed/ + C++ collective runtime):
  env.py        — init_parallel_env / rank/world (jax.distributed bootstrap)
  topology.py   — CommunicateTopology / HybridCommunicateGroup → jax Mesh
  comm.py       — eager collective API (shard_map programs over ICI)
  fleet/        — fleet facade, DistributedStrategy, hybrid-parallel layers
  parallel.py   — DataParallel
  sharding/     — ZeRO stage 1/2/3 (group_sharded_parallel)
  auto_parallel — ProcessMesh / shard_tensor / reshard (DistTensor parity)
  checkpoint/   — sharded save/load with reshard-on-load
  launch/       — process launcher CLI
"""

from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, is_initialized,
)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup, ProcessGroup, new_group,
    get_hybrid_communicate_group, global_mesh,
)
from .comm import (  # noqa: F401
    ReduceOp, all_reduce, all_gather, all_gather_object, gather,
    get_group, split,
    scatter_object_list, broadcast_object_list, reduce_scatter,
    alltoall, alltoall_single, broadcast, reduce, scatter, barrier, send, recv,
    shard_stack, unstack, ppermute_shift, wait, stream,
    isend, irecv, P2POp, batch_isend_irecv, reduce_scatter_tensor,
    all_gather_into_tensor, monitored_barrier, get_backend,
    destroy_process_group,
)
from . import launch  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from . import fleet  # noqa: F401
from .auto_parallel_api import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, dtensor_from_fn,
    reshard, shard_layer, get_mesh, set_mesh, unshard_dtensor, to_distributed,
)
from . import auto_parallel  # noqa: F401
from . import passes  # noqa: F401
from . import rpc  # noqa: F401
from . import utils  # noqa: F401
from .auto_parallel.parallelize import (  # noqa: F401
    ColWiseParallel, RowWiseParallel, parallelize,
)
from .utils import global_gather, global_scatter  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import group_sharded_parallel  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from .store import TCPStore, Store  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Parity: paddle.distributed.spawn. On TPU the SPMD model drives all
    local devices from ONE process, so spawn degenerates to calling ``func``
    once with the mesh active (per-device process fan-out is an anti-pattern
    on TPU; multi-host fan-out is the launcher's job)."""
    init_parallel_env()
    func(*args)

from . import comm as communication  # noqa: F401,E402  (module path parity)
from . import comm as collective  # noqa: F401,E402


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Parity: CPU-only bootstrap — same coordination path here."""
    return init_parallel_env()


def parallel_with_gloo():  # pragma: no cover - trivial parity shim
    return init_parallel_env()
