"""Eager collective API.

Parity surface: python/paddle/distributed/communication/ (all_reduce,
all_gather, reduce_scatter, alltoall, broadcast, reduce, scatter, barrier,
send/recv) over ProcessGroupNCCL (upstream
paddle/fluid/distributed/collective/process_group_nccl.cc). TPU-native
design (SURVEY.md §5 north-star item): collectives are tiny jit-compiled
``shard_map`` programs over the active mesh — XLA schedules them on ICI.

Rank model: one process drives the whole mesh (SPMD), so a "per-rank tensor"
is represented RANK-STACKED — a Tensor whose leading axis is the group size,
sharded over the group's mesh axis (shard i = rank i's local value). Build
one with ``shard_stack([v0, v1, ...], group)``; read back per-rank values
with ``unstack``. Under multi-process deployment the same programs run with
jax.distributed global arrays unchanged.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, to_tensor
from .topology import ProcessGroup, global_mesh

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "all_gather_object",
    "scatter_object_list", "broadcast_object_list",
    "reduce_scatter", "alltoall", "alltoall_single", "broadcast", "reduce",
    "scatter", "barrier", "send", "recv", "ppermute_shift", "shard_stack",
    "unstack", "wait", "stream",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _resolve_group(group: Optional[ProcessGroup]):
    if group is None:
        mesh = global_mesh()
        return mesh, mesh.axis_names[0]
    return group.mesh, group.axis_name


def _ensure_stacked(t: Tensor, mesh: Mesh, axis: str) -> Tensor:
    """Validate/shard the rank-stacked layout (leading dim == group size)."""
    g = int(mesh.shape[axis])
    if t._data.shape[0] != g:
        raise ValueError(
            f"eager collectives take rank-stacked tensors: leading dim must "
            f"be the group size {g}, got shape {tuple(t._data.shape)}. Build "
            f"one with paddle.distributed.shard_stack([...], group)")
    spec = P(axis, *([None] * (t._data.ndim - 1)))
    arr = jax.device_put(t._data, NamedSharding(mesh, spec))
    return Tensor(arr, stop_gradient=t.stop_gradient)


def shard_stack(tensors: List[Tensor], group: Optional[ProcessGroup] = None) -> Tensor:
    """Stack per-rank local values into the rank-stacked sharded layout."""
    mesh, axis = _resolve_group(group)
    arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in tensors]
    stacked = jnp.stack(arrs, axis=0)
    spec = P(axis, *([None] * (stacked.ndim - 1)))
    return Tensor(jax.device_put(stacked, NamedSharding(mesh, spec)))


def unstack(t: Tensor, group: Optional[ProcessGroup] = None) -> List[Tensor]:
    return [Tensor(t._data[i]) for i in range(t._data.shape[0])]


@functools.lru_cache(maxsize=256)
def _collective_fn(kind: str, mesh: Mesh, axis: str, extra=None):
    """Build + jit one collective program for (kind, mesh, axis)."""
    spec = P(axis)

    def reduce_local(x, op):
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, axis)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, axis)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, axis)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, axis)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(jnp.abs(x)), axis)) * \
                _sign_prod(x)
        raise ValueError(f"unknown reduce op {op}")

    def _sign_prod(x):
        neg = jax.lax.psum((x < 0).astype(jnp.int32), axis)
        return jnp.where(neg % 2 == 0, 1.0, -1.0).astype(x.dtype)

    if kind == "all_reduce":
        op = extra

        def f(x):
            return reduce_local(x, op)
    elif kind == "all_gather":
        def f(x):
            # local (1, ...) -> (g, ...) everywhere; rank-stacked out keeps
            # the gathered block per shard
            return jax.lax.all_gather(x[0], axis)
    elif kind == "reduce_scatter":
        op = extra

        def f(x):
            s = reduce_local(x, op)  # (1, m, ...)
            g = jax.lax.axis_size(axis)
            i = jax.lax.axis_index(axis)
            m = s.shape[1] // g
            return jax.lax.dynamic_slice_in_dim(s, i * m, m, axis=1)
    elif kind == "alltoall":
        def f(x):
            # local (1, g, ...): chunk j goes to rank j; received stacked back
            # along the same dim
            return jnp.swapaxes(
                jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=0), 0, 1)
    elif kind == "broadcast":
        src = extra

        def f(x):
            gathered = jax.lax.all_gather(x[0], axis)  # (g, ...)
            return gathered[src][None]
    elif kind == "reduce":
        op, dst = extra

        def f(x):
            r = reduce_local(x, op)
            i = jax.lax.axis_index(axis)
            return jnp.where(i == dst, r, x)
    elif kind == "scatter":
        src = extra

        def f(x):
            # x local (1, g, ...): take src's row j for rank j
            all_rows = jax.lax.all_gather(x[0], axis)  # (g, g, ...)
            i = jax.lax.axis_index(axis)
            return all_rows[src][i][None]
    elif kind == "shift":
        offset = extra

        def f(x):
            g = jax.lax.axis_size(axis)
            perm = [(i, (i + offset) % g) for i in range(g)]
            return jax.lax.ppermute(x, axis, perm)
    else:
        raise ValueError(kind)

    mapped = jax.shard_map(f, mesh=mesh, in_specs=(spec,), out_specs=spec)
    return jax.jit(mapped)


def all_reduce(tensor: Tensor, op: str = ReduceOp.SUM,
               group: Optional[ProcessGroup] = None, sync_op: bool = True):
    mesh, axis = _resolve_group(group)
    t = _ensure_stacked(tensor, mesh, axis)
    out = _collective_fn("all_reduce", mesh, axis, op)(t._data)
    tensor._set_data(out)
    return tensor


def all_gather(tensor_list: List[Tensor], tensor: Tensor,
               group: Optional[ProcessGroup] = None, sync_op: bool = True):
    mesh, axis = _resolve_group(group)
    g = int(mesh.shape[axis])
    t = _ensure_stacked(tensor, mesh, axis)
    out = _collective_fn("all_gather", mesh, axis)(t._data)
    # global out is (g*g, ...): g identical gathered blocks; take block 0
    rows = out.reshape((g, g) + tuple(out.shape[1:]))[0] if out.shape[0] == g * g \
        else out
    for i in range(g):
        tensor_list.append(Tensor(rows[i]))
    return tensor_list


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list,
                   op: str = ReduceOp.SUM,
                   group: Optional[ProcessGroup] = None, sync_op: bool = True):
    mesh, axis = _resolve_group(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        # list of g rank-stacked tensors, entry j destined for rank j
        src = Tensor(jnp.concatenate([t._data for t in src], axis=1))
    t = _ensure_stacked(src, mesh, axis)
    out = _collective_fn("reduce_scatter", mesh, axis, op)(t._data)
    tensor._set_data(out)
    return tensor


def alltoall(out_tensor_list, in_tensor_list,
             group: Optional[ProcessGroup] = None, sync_op: bool = True):
    """paddle.distributed.alltoall: rank i sends in_list[j] to rank j."""
    mesh, axis = _resolve_group(group)
    g = int(mesh.shape[axis])
    # build rank-stacked (g, g, ...) from a rank-stacked list: in_tensor_list
    # holds g rank-stacked tensors (each (g, ...)), entry j = what every rank
    # sends to rank j
    stacked = jnp.stack([t._data for t in in_tensor_list], axis=1)  # (g, g, ...)
    spec = P(axis, *([None] * (stacked.ndim - 1)))
    arr = jax.device_put(stacked, NamedSharding(mesh, spec))
    out = _collective_fn("alltoall", mesh, axis)(arr)
    for j in range(g):
        out_tensor_list.append(Tensor(out[:, j]))
    return out_tensor_list


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    mesh, axis = _resolve_group(group)
    g = int(mesh.shape[axis])
    t = _ensure_stacked(in_tensor, mesh, axis)
    # each rank's local (m, ...) splits into g chunks along its dim 0
    x = t._data.reshape(g, g, t._data.shape[1] // g, *t._data.shape[2:])
    spec = P(axis, *([None] * (x.ndim - 1)))
    arr = jax.device_put(x, NamedSharding(mesh, spec))
    out = _collective_fn("alltoall", mesh, axis)(arr)
    res = out.reshape(t._data.shape)
    out_tensor._set_data(res)
    return out_tensor


def broadcast(tensor: Tensor, src: int = 0,
              group: Optional[ProcessGroup] = None, sync_op: bool = True):
    mesh, axis = _resolve_group(group)
    t = _ensure_stacked(tensor, mesh, axis)
    out = _collective_fn("broadcast", mesh, axis, src)(t._data)
    tensor._set_data(out)
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op: str = ReduceOp.SUM,
           group: Optional[ProcessGroup] = None, sync_op: bool = True):
    mesh, axis = _resolve_group(group)
    t = _ensure_stacked(tensor, mesh, axis)
    out = _collective_fn("reduce", mesh, axis, (op, dst))(t._data)
    tensor._set_data(out)
    return tensor


def scatter(tensor: Tensor, tensor_list=None, src: int = 0,
            group: Optional[ProcessGroup] = None, sync_op: bool = True):
    mesh, axis = _resolve_group(group)
    g = int(mesh.shape[axis])
    if tensor_list is not None:
        stacked = jnp.stack([t._data for t in tensor_list], axis=0)  # (g, ...)
        stacked = jnp.broadcast_to(stacked[None], (g,) + stacked.shape)
    else:
        stacked = tensor._data
    spec = P(axis, *([None] * (stacked.ndim - 1)))
    arr = jax.device_put(stacked, NamedSharding(mesh, spec))
    out = _collective_fn("scatter", mesh, axis, src)(arr)
    tensor._set_data(out)
    return tensor


def barrier(group: Optional[ProcessGroup] = None):
    mesh, axis = _resolve_group(group)
    g = int(mesh.shape[axis])
    token = shard_stack([to_tensor(np.zeros((), np.float32))] * g, group)
    all_reduce(token, group=group)
    token.numpy()  # block


def ppermute_shift(tensor: Tensor, offset: int = 1,
                   group: Optional[ProcessGroup] = None) -> Tensor:
    """Rotate rank-stacked values by ``offset`` along the group ring (the
    building block for pipeline p2p and ring attention)."""
    mesh, axis = _resolve_group(group)
    t = _ensure_stacked(tensor, mesh, axis)
    out = _collective_fn("shift", mesh, axis, offset)(t._data)
    return Tensor(out)


def send(tensor: Tensor, dst: int = 0, group=None, sync_op=True):
    """P2P send/recv parity: in SPMD these fuse into one ppermute; the eager
    emulation stores the in-flight value on the group."""
    mesh, axis = _resolve_group(group)
    _P2P_BUF[(id(mesh), axis, dst)] = Tensor(tensor._data)
    return tensor


def recv(tensor: Tensor, src: int = 0, group=None, sync_op=True):
    mesh, axis = _resolve_group(group)
    for key, val in list(_P2P_BUF.items()):
        if key[0] == id(mesh) and key[1] == axis:
            tensor._set_data(val._data)
            del _P2P_BUF[key]
            return tensor
    raise RuntimeError("recv without matching send (eager p2p emulation)")


_P2P_BUF = {}


def all_gather_object(object_list: List, obj, group=None):
    """Process-level object gather (single-process SPMD: the one process's
    object is the only real object)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            np.frombuffer(__import__("pickle").dumps(obj), np.uint8))
        raise NotImplementedError("multi-host object gather: use broadcast")
    object_list.append(obj)
    return object_list


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src: int = 0, group=None):
    """Process-level object scatter (parity:
    paddle.distributed.scatter_object_list). Single-process SPMD: rank 0 is
    the only process, so it keeps its own slot."""
    import jax
    if jax.process_count() > 1:
        raise NotImplementedError(
            "multi-host object scatter: broadcast the full list and index by "
            "rank (object collectives ride the coordination plane, not ICI)")
    out_object_list.clear()
    out_object_list.append(in_object_list[0] if in_object_list else None)
    return out_object_list


def broadcast_object_list(object_list: List, src: int = 0, group=None):
    """Parity: paddle.distributed.broadcast_object_list (single-process:
    identity; the src process's objects are already local)."""
    import jax
    if jax.process_count() > 1:
        raise NotImplementedError(
            "multi-host object broadcast: serialize via the TCPStore "
            "coordination plane")
    return object_list


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        jax.block_until_ready(tensor._data)


class stream:
    """Parity namespace: paddle.distributed.stream.* maps to the same sync
    collectives (XLA owns streams)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
    reduce = staticmethod(reduce)
    send = staticmethod(send)
    recv = staticmethod(recv)


# --- p2p / torch-style aliases ----------------------------------------------

class _CompletedTask:
    """Parity handle for async ops: collectives here execute via XLA when
    the value is consumed, so the task is complete-on-creation."""

    def __init__(self, result=None):
        self._result = result

    def wait(self):
        return self._result

    def is_completed(self) -> bool:
        return True


def isend(tensor: Tensor, dst: int = 0, group=None):
    send(tensor, dst=dst, group=group, sync_op=False)
    return _CompletedTask(tensor)


def irecv(tensor: Tensor, src: int = 0, group=None):
    recv(tensor, src=src, group=group, sync_op=False)
    return _CompletedTask(tensor)


class P2POp:
    """Parity: paddle.distributed.P2POp — one batched point-to-point op."""

    def __init__(self, op, tensor: Tensor, peer: int, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Run a batch of P2POps; XLA already coalesces the underlying
    collectives inside one program, so this is a sequential dispatch that
    returns completed tasks."""
    tasks = []
    for p2p in p2p_op_list:
        tasks.append(p2p.op(p2p.tensor, p2p.peer, group=p2p.group))
    return [t if isinstance(t, _CompletedTask) else _CompletedTask(t)
            for t in tasks]


def reduce_scatter_tensor(output: Tensor, input: Tensor, op=None, group=None,
                          sync_op=True):
    """torch-style alias of reduce_scatter (paddle keeps both spellings)."""
    return reduce_scatter(output, input,
                          op=op if op is not None else ReduceOp.SUM,
                          group=group)


def all_gather_into_tensor(output: Tensor, input: Tensor, group=None,
                           sync_op=True):
    parts: List[Tensor] = []
    all_gather(parts, input, group=group)
    out = jnp.concatenate([p._data for p in parts], axis=0)
    output._set_data(out)
    return output


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Barrier with failure attribution in the reference; ICI barriers are
    compiler-scheduled so this is the plain barrier."""
    return barrier(group=group)


def get_backend(group=None) -> str:
    """The collective backend name: XLA over ICI/DCN (the reference returns
    'NCCL'/'GLOO')."""
    return "XLA"


def destroy_process_group(group=None) -> None:
    """Tear down eager collective state (parity: the reference frees the
    NCCL comms; here the mesh/collective caches)."""
    from . import env as _env
    if group is None:
        _env._initialized = False


def gather(tensor: Tensor, gather_list=None, dst: int = 0,
           group: Optional["ProcessGroup"] = None, sync_op: bool = True):
    """Gather to ``dst`` (reference: paddle.distributed.gather). SPMD
    note: on a mesh every device executes the program, so the gather is an
    all_gather with non-dst ranks discarding — the list FILLS (replacing
    prior contents, so loops can reuse it) only for the dst 'rank view',
    matching the reference contract that gather_list is meaningful on
    dst."""
    tmp: List[Tensor] = []
    all_gather(tmp, tensor, group=group, sync_op=sync_op)
    if gather_list is not None:
        gather_list[:] = tmp
    return gather_list


_WORLD_GROUP = None


def get_group(id: int = 0):
    """Parity: paddle.distributed.get_group — look up a group handle by
    its id (groups register at construction; gid 0 is reserved). id 0 — or
    an id never issued — resolves to the world group over the GLOBAL
    1-axis device mesh (never a hybrid sub-axis)."""
    from .topology import ProcessGroup, global_mesh
    if id != 0:
        g = ProcessGroup._registry.get(id)
        if g is not None:
            return g
    global _WORLD_GROUP
    mesh = global_mesh()
    if _WORLD_GROUP is None or _WORLD_GROUP.mesh is not mesh:
        _WORLD_GROUP = ProcessGroup(mesh, mesh.axis_names[0])
        _WORLD_GROUP.id = 0
        ProcessGroup._registry[0] = _WORLD_GROUP
    return _WORLD_GROUP


_SPLIT_LAYERS: dict = {}


def split(x, size, operation: str = "linear", axis: int = 0,
          num_partitions: int = 1, gather_out: bool = True,
          weight_attr=None, bias_attr=None, name=None):
    """Functional model-parallel op (reference: paddle.distributed.split —
    the fleet static-graph API for splitting a linear/embedding across the
    mp group). The parallel layer is created on first call and cached by
    ``name`` — REQUIRED, like the reference's unique-parameter-name
    contract (an anonymous cache key would silently share weights between
    unrelated call sites). The cache is scoped to the active hybrid
    topology: re-initializing fleet invalidates it (a layer sharded for a
    4-way mp mesh must not serve a 2-way one).

    operation='linear': axis=1 splits the weight's columns
    (ColumnParallelLinear, ``gather_out`` controls output gathering),
    axis=0 splits its rows (RowParallelLinear). operation='embedding'
    splits the vocabulary (VocabParallelEmbedding)."""
    from .fleet.mp_layers import (ColumnParallelLinear, RowParallelLinear,
                                  VocabParallelEmbedding)
    from .topology import get_hybrid_communicate_group

    if name is None:
        raise ValueError(
            "paddle.distributed.split requires a unique name= per weight "
            "(the reference's parameter-naming requirement)")
    from .topology import topology_epoch
    hcg = get_hybrid_communicate_group()
    mp = hcg.get_model_parallel_world_size() if hcg is not None else 1
    if num_partitions not in (1, mp):
        raise ValueError(
            f"num_partitions={num_partitions} disagrees with the active "
            f"mp degree {mp}")
    epoch = topology_epoch()
    if _SPLIT_LAYERS.get("_epoch") != epoch:
        _SPLIT_LAYERS.clear()  # topology changed: old shardings are stale
        _SPLIT_LAYERS["_epoch"] = epoch
    key = name
    layer = _SPLIT_LAYERS.get(key)
    if layer is None:
        if operation == "linear":
            if axis == 1:
                layer = ColumnParallelLinear(size[0], size[1],
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr,
                                             gather_output=gather_out,
                                             name=key)
            elif axis == 0:
                # the functional API feeds a replicated activation
                layer = RowParallelLinear(size[0], size[1],
                                          weight_attr=weight_attr,
                                          bias_attr=bias_attr,
                                          input_is_parallel=False, name=key)
            else:
                raise ValueError("linear split axis must be 0 or 1")
        elif operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr, name=key)
        else:
            raise ValueError(f"unknown split operation {operation!r}")
        _SPLIT_LAYERS[key] = layer
    return layer(x)
