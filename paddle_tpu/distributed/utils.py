"""``paddle.distributed.utils`` — MoE dispatch primitives and helpers.

Parity: python/paddle/distributed/utils/moe_utils.py (global_scatter /
global_gather — the variable-count token exchange under the reference's MoE).

TPU-native note: ragged sends don't exist on ICI; the in-graph MoE path here
is ``incubate.moe.MoELayer``'s dense padded all-to-all (capacity-bucketed),
which is what the XLA MoE stacks do. These functions provide the eager API:
exact single-process semantics (expert grouping/restore), and on a real
multi-process world they route through the padded all-to-all with
per-(rank, expert) counts.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from .env import get_world_size

__all__ = ["global_scatter", "global_gather"]


def _counts(t) -> np.ndarray:
    arr = t._data if isinstance(t, Tensor) else t
    return np.asarray(arr).reshape(-1).astype(np.int64)


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """Send rows of ``x`` (grouped by destination expert) to the owning
    ranks. ``local_count[i*ne+e]`` rows go to expert e of rank i."""
    x = x if isinstance(x, Tensor) else to_tensor(x)
    lc = _counts(local_count)
    world = get_world_size()
    if world <= 1:
        # all experts local: the rows are already expert-grouped
        return Tensor(x._data[: int(lc.sum())])
    raise NotImplementedError(
        "multi-process global_scatter: use incubate.moe.MoELayer's dense "
        "padded all-to-all dispatch (ragged sends don't exist on ICI; the "
        "capacity-bucketed exchange is the TPU-native form)")


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter: return received rows to their senders."""
    x = x if isinstance(x, Tensor) else to_tensor(x)
    gc = _counts(global_count)
    world = get_world_size()
    if world <= 1:
        return Tensor(x._data[: int(gc.sum())])
    raise NotImplementedError(
        "multi-process global_gather: use incubate.moe.MoELayer's combine "
        "path (dense padded all-to-all)")
