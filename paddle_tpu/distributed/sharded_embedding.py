"""Sharded embedding: the PS → ICI path.

Parity surface (BASELINE config #5 north-star item): the reference trains
sparse-embedding models (DeepFM) against a brpc parameter server hosting
``MemorySparseTable`` shards (upstream paddle/fluid/distributed/ps/). The
TPU replacement per the north star ("PS → ICI allreduce path"): the table is
a DENSE tensor row-sharded over the mesh; lookups are XLA gathers that ride
ICI to the owning shard, and gradients reduce-scatter back — no RPC, no
separate server processes, exact (non-async) updates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.initializer import XavierUniform
from ..nn.layer import Layer
from .topology import get_hybrid_communicate_group, global_mesh

__all__ = ["ShardedEmbedding"]


class ShardedEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, axis: str = None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        # sparse=True: gradients come back as SelectedRows (rows, values) —
        # the SelectedRows path PaddleRec tables rely on; push_sparse ships
        # exactly those rows (see push_sparse_grad)
        self.sparse = sparse
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierUniform())
        mesh, ax = self._resolve_axis(axis)
        if mesh is not None and num_embeddings % int(mesh.shape[ax]) == 0:
            self.weight._set_data(jax.device_put(
                self.weight._data, NamedSharding(mesh, P(ax, None))))
            self.weight.is_distributed = True
        # make the table reachable by the PS-mode async Communicator
        from .communicator import register_sparse_table
        register_sparse_table(name or self.weight.name, self.weight)

    @staticmethod
    def _resolve_axis(axis):
        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            for cand in ([axis] if axis else []) + ["mp", "sharding", "dp"]:
                if cand in hcg.mesh.axis_names and int(hcg.mesh.shape[cand]) > 1:
                    return hcg.mesh, cand
        mesh = global_mesh()
        ax = axis or mesh.axis_names[0]
        if int(mesh.shape[ax]) > 1:
            return mesh, ax
        return None, None

    def forward(self, ids):
        return F.embedding(ids, self.weight, padding_idx=self.padding_idx,
                           sparse=self.sparse)

    def push_sparse_grad(self, communicator, table_name=None) -> bool:
        """Ship this table's accumulated gradient to the PS communicator as
        sparse (rows, values) traffic — the upstream push_sparse payload —
        and clear it. A dense gradient (sparse=False) ships every row;
        returns False when there is nothing to push."""
        from ..core.selected_rows import SelectedRowsTensor

        g = self.weight.grad
        if g is None:
            return False
        name = table_name or self.weight.name
        if isinstance(g, SelectedRowsTensor) and g.is_selected_rows():
            sr = g.selected_rows.merged()
            communicator.push_sparse(name, sr.rows, sr.values)
        else:
            communicator.push_sparse(
                name, jnp.arange(self.num_embeddings, dtype=jnp.int32),
                g._data)
        self.weight.clear_grad()
        return True
