"""Distributed environment bootstrap.

Parity surface: python/paddle/distributed/parallel.py ``init_parallel_env`` +
env-var contract (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_TRAINER_ENDPOINTS) and the C++ TCPStore rendezvous (upstream
paddle/phi/core/distributed/store/). TPU-native design: the process model is
one process per HOST (jax norm), not per device; rendezvous is
``jax.distributed.initialize`` against the coordination service — the
TCPStore equivalent. Inside a process, "ranks" are mesh positions: the
eager collective API operates on group-stacked sharded arrays (see comm.py).
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "is_initialized", "local_device_count",
]

_initialized = False


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v else default


def init_parallel_env(strategy=None):
    """Initialize the distributed context.

    Multi-host: if the paddle launcher env contract is present
    (PADDLE_TRAINERS_NUM > 1), call ``jax.distributed.initialize`` with the
    first endpoint as coordinator. Single-host: no-op beyond building the
    default topology; the local device mesh carries all parallelism.
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    nprocs = _env_int("PADDLE_TRAINERS_NUM", 1)
    pid = _env_int("PADDLE_TRAINER_ID", 0)
    # probe the coordination-service state WITHOUT jax.process_count(): that
    # would initialize the XLA backend, after which initialize() refuses
    try:
        from jax._src import distributed as _jdist
        already = _jdist.global_state.client is not None
    except Exception:
        # private-API drift: fall back to the (backend-initializing) probe
        already = jax.process_count() > 1
    if nprocs > 1 and not already:
        endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        coordinator = endpoints[0] if endpoints and endpoints[0] else None
        try:
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=nprocs, process_id=pid)
        except RuntimeError as e:
            if "already" not in str(e):
                raise  # a real bootstrap failure, not double-init
    from .topology import _ensure_default_topology
    _ensure_default_topology()
    # elastic launcher present? lease a heartbeat so the manager can tell a
    # hung worker from a training one (no-op without PADDLE_ELASTIC_MASTER)
    from .fleet.elastic import start_worker_heartbeat
    start_worker_heartbeat(rank=pid)
    _initialized = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized


def get_rank(group=None) -> int:
    """Process-level rank (paddle's trainer id). Inside SPMD programs, use
    mesh axis indices instead."""
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except RuntimeError:
        return 0


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    try:
        if jax.process_count() > 1:
            return jax.process_count()
    except RuntimeError:
        pass  # backend not initialized yet: single-process by definition
    return 1


def local_device_count() -> int:
    return jax.local_device_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        return 0

    @property
    def current_endpoint(self) -> str:
        eps = self.trainer_endpoints
        return eps[self.rank] if self.rank < len(eps) else ""

    @property
    def trainer_endpoints(self) -> List[str]:
        return [e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def local_rank(self) -> int:
        return self.rank
