"""``paddle.distributed.passes`` — distributed graph-pass registry.

Parity: python/paddle/distributed/passes/ (new_pass, PassManager; upstream
passes rewrite static programs for amp/recompute/sharding/fusion). On this
runtime those rewrites are jax transforms + XLA fusion inside ``to_static``;
the registry keeps the API so reference strategy code drives the same knobs:
each named pass maps to the equivalent framework switch where one exists and
records itself otherwise (pass-applied programs compile through XLA, which
already performs the fusion/scheduling passes these names request).
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["new_pass", "PassManager", "PassContext"]

_KNOWN = {
    # name -> short effect note (what the XLA path already covers)
    "fuse_elewise_add_act": "XLA elementwise fusion",
    "fuse_bn_act": "XLA elementwise fusion",
    "fuse_gemm_epilogue": "XLA matmul epilogue fusion",
    "fused_attention": "SDPA/flash routing",
    "fused_feedforward": "XLA fusion",
    "auto_parallel_amp": "amp.auto_cast inside to_static",
    "auto_parallel_fp16": "amp.auto_cast(level=O2)",
    "auto_parallel_recompute": "fleet.utils.recompute",
    "auto_parallel_sharding": "sharding.DygraphShardingOptimizer",
    "auto_parallel_gradient_merge": "gradient accumulation",
}


class PassContext:
    def __init__(self):
        self.attrs: Dict = {}


class _Pass:
    def __init__(self, name: str, attrs: Optional[Dict] = None):
        self.name = name
        self.attrs = dict(attrs or {})
        self.applied = False

    def apply(self, main_programs=None, startup_programs=None, context=None):
        """Record application; program rewriting is XLA's job here."""
        self.applied = True
        return context or PassContext()

    def __repr__(self):
        note = _KNOWN.get(self.name, "no-op under XLA")
        return f"Pass({self.name}: {note})"


def new_pass(name: str, pass_attrs: Optional[Dict] = None) -> _Pass:
    return _Pass(name, pass_attrs)


class PassManager:
    def __init__(self, passes: Optional[List[_Pass]] = None):
        self._passes = list(passes or [])

    def append(self, p: _Pass) -> None:
        self._passes.append(p)

    def apply(self, main_programs=None, startup_programs=None):
        ctx = PassContext()
        for p in self._passes:
            p.apply(main_programs, startup_programs, ctx)
        return ctx

    @property
    def names(self):
        return [p.name for p in self._passes]
