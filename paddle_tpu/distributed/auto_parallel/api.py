"""``paddle.distributed.auto_parallel.api`` — stable-API module path.

Re-exports the DTensor surface plus the parallelize plan classes.
"""

from ..auto_parallel_api import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard, dtensor_from_fn,
    get_mesh, reshard, set_mesh, shard_layer, shard_tensor,
)
from .parallelize import (  # noqa: F401
    ColWiseParallel, PrepareLayerInput, PrepareLayerOutput, RowWiseParallel,
    SequenceParallelBegin, SequenceParallelEnd, parallelize,
)
