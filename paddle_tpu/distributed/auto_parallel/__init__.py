"""``paddle.distributed.auto_parallel`` package facade.

Parity: python/paddle/distributed/auto_parallel/. The implementation lives
in ``distributed/auto_parallel_api.py`` (ProcessMesh / placements /
shard_tensor / reshard over jax.sharding); this package provides the
upstream import paths (``auto_parallel.api``, ``ProcessMesh`` at package
level).
"""

from ..auto_parallel_api import (  # noqa: F401
    Partial, Placement, ProcessMesh, Replicate, Shard, dtensor_from_fn,
    get_mesh, reshard, set_mesh, shard_layer, shard_tensor,
)
from . import api  # noqa: F401
