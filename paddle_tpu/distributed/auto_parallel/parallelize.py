"""``paddle.distributed.parallelize`` — plan-driven model parallelization.

Parity: python/paddle/distributed/auto_parallel/intermediate/ (parallelize
with dp/mp/pp configs, ColWiseParallel/RowWiseParallel plans). TPU-native
design: a plan entry shards the matched layer's parameters over the mesh's
``mp`` axis with jax NamedShardings — XLA inserts the TP collectives; dp
config shards the batch (callers place inputs); pp config is routed to the
pipeline engine which has its own schedule machinery.
"""

from __future__ import annotations

import fnmatch
from typing import Dict, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn.layer import Layer
from ..auto_parallel_api import ProcessMesh, get_mesh

__all__ = ["parallelize", "plan_parallelize", "ColWiseParallel",
           "RowWiseParallel",
           "PrepareLayerInput", "PrepareLayerOutput",
           "SequenceParallelBegin", "SequenceParallelEnd"]


class _Plan:
    def apply(self, layer: Layer, mesh: ProcessMesh, axis: str) -> None:
        raise NotImplementedError


class ColWiseParallel(_Plan):
    """Shard the output dimension of a Linear/Embedding weight over ``mp``:
    weight (in, out) -> P(None, 'mp'); bias (out,) -> P('mp')."""

    def __init__(self, gather_output: bool = False):
        self.gather_output = gather_output

    def apply(self, layer, mesh, axis):
        w = getattr(layer, "weight", None)
        if w is not None:
            spec = [None] * (w._data.ndim - 1) + [axis]
            w._set_data(jax.device_put(
                w._data, NamedSharding(mesh.jax_mesh, P(*spec))))
        b = getattr(layer, "bias", None)
        if b is not None:
            b._set_data(jax.device_put(
                b._data, NamedSharding(mesh.jax_mesh, P(axis))))


class RowWiseParallel(_Plan):
    """Shard the input dimension over ``mp``: weight (in, out) ->
    P('mp', None); bias replicated."""

    def __init__(self, is_input_parallel: bool = True):
        self.is_input_parallel = is_input_parallel

    def apply(self, layer, mesh, axis):
        w = getattr(layer, "weight", None)
        if w is not None:
            spec = [axis] + [None] * (w._data.ndim - 1)
            w._set_data(jax.device_put(
                w._data, NamedSharding(mesh.jax_mesh, P(*spec))))
        b = getattr(layer, "bias", None)
        if b is not None:
            b._set_data(jax.device_put(
                b._data, NamedSharding(mesh.jax_mesh, P())))


class PrepareLayerInput(_Plan):
    def __init__(self, fn=None):
        self.fn = fn

    def apply(self, layer, mesh, axis):
        if self.fn is not None:
            layer.register_forward_pre_hook(
                lambda l, inp: self.fn(inp, process_mesh=mesh))


class PrepareLayerOutput(_Plan):
    def __init__(self, fn=None):
        self.fn = fn

    def apply(self, layer, mesh, axis):
        if self.fn is not None:
            layer.register_forward_post_hook(
                lambda l, inp, out: self.fn(out, process_mesh=mesh))


class SequenceParallelBegin(_Plan):
    """Marker plans: sequence-parallel scatter/gather boundaries are sharding
    constraints under jit; eager keeps the layer untouched."""

    def apply(self, layer, mesh, axis):
        pass


class SequenceParallelEnd(SequenceParallelBegin):
    pass


def _match_layers(model: Layer, pattern: str):
    for name, sub in model.named_sublayers():
        if fnmatch.fnmatch(name, pattern):
            yield name, sub


# name fragments that identify the two halves of a megatron pair; checked
# before the structural fallback (registration order) in the planner
_COL_HINTS = ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj", "qkv",
              "in_proj", "fc1", "linear1", "w1", "wi")
_ROW_HINTS = ("o_proj", "down_proj", "out_proj", "fc2", "linear2", "w2",
              "wo")


def plan_parallelize(model: Layer, mesh: ProcessMesh,
                     axis: Optional[str] = None) -> Dict[str, _Plan]:
    """Derive a tensor-parallel plan from the model structure (the
    sharding-planner seam the reference grows a cost model behind —
    upstream python/paddle/distributed/auto_parallel/ planners; ours is a
    structural heuristic, documented and testable):

    * Linear layers pair up megatron-style WITHIN each parent module:
      name hints first (q/k/v/gate/up → column, o/down/fc2 → row), then
      registration order (all but the last linear column, the last row) —
      so one block contributes ONE all-reduce, after the row projection;
    * only divisible layers shard (column: out %% size, row: in %% size);
      indivisible layers stay replicated (never a wrong layout);
    * a lone linear in a module stays replicated (no pair, sharding it
      would buy an all-gather for nothing).

    Returns {qualified-name: Plan}, directly usable as
    ``mp_config.parallelize_plan`` (or pass ``"auto"`` there).
    """
    from ...nn import Linear

    ax = axis or ("mp" if "mp" in mesh.dim_names else mesh.dim_names[-1])
    size = mesh.get_dim_size(ax)
    plan: Dict[str, _Plan] = {}

    def divisible_col(l):  # noqa: E743
        return l.weight._data.shape[1] % size == 0

    def divisible_row(l):  # noqa: E743
        return l.weight._data.shape[0] % size == 0

    for parent_name, parent in model.named_sublayers(include_self=True):
        linears = [(n, c) for n, c in parent.named_children()
                   if isinstance(c, Linear)]
        if len(linears) < 2:
            continue
        cols, rows, unknown = [], [], []
        for n, c in linears:
            ln = n.lower()
            if any(h in ln for h in _COL_HINTS):
                cols.append((n, c))
            elif any(h in ln for h in _ROW_HINTS):
                rows.append((n, c))
            else:
                unknown.append((n, c))
        # hint-less linears pair ADJACENTLY (registration order) into
        # (col, row); an odd leftover stays replicated — a col without a
        # row partner (or two cols in a row) would force an extra
        # mid-block collective
        for j in range(len(unknown) // 2):
            cols.append(unknown[2 * j])
            rows.append(unknown[2 * j + 1])
        if not cols or not rows:
            continue
        usable_cols = [(n, c) for n, c in cols if divisible_col(c)]
        usable_rows = [(n, c) for n, c in rows if divisible_row(c)]
        if not usable_cols or not usable_rows:
            continue  # half a pair would add comms without saving memory
        prefix = parent_name + "." if parent_name else ""
        for n, _c in usable_cols:
            plan[prefix + n] = ColWiseParallel()
        for n, _c in usable_rows:
            plan[prefix + n] = RowWiseParallel()
    return plan


def parallelize(model: Layer, optimizer=None,
                mesh: Optional[ProcessMesh] = None,
                config: Optional[Dict] = None):
    """Apply a hybrid-parallel ``config`` to ``model`` (reference:
    paddle.distributed.parallelize).

    config = {"mp_config": {"parallelize_plan": {"pattern": Plan} | "auto"},
              "dp_config": {"sharding_level": 0|1|2|3},
              "pp_config": {...}}

    ``parallelize_plan="auto"`` runs :func:`plan_parallelize`.
    """
    config = config or {}
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("parallelize needs a mesh: pass mesh= or call "
                         "paddle.distributed.set_mesh(...) first")
    mp_axis = "mp" if "mp" in mesh.dim_names else mesh.dim_names[-1]

    mp_cfg = config.get("mp_config") or {}
    plan = mp_cfg.get("parallelize_plan") or {}
    if plan == "auto":
        plan = plan_parallelize(model, mesh, mp_axis)
    for pattern, plan_obj in plan.items():
        plans = plan_obj if isinstance(plan_obj, (list, tuple)) else [plan_obj]
        for _, sub in _match_layers(model, pattern):
            for p in plans:
                p.apply(sub, mesh, mp_axis)

    dp_cfg = config.get("dp_config") or {}
    level = int(dp_cfg.get("sharding_level", 0) or 0)
    if optimizer is not None and level:
        from ..sharding import DygraphShardingOptimizer
        optimizer = DygraphShardingOptimizer(optimizer, stage=level)
    if optimizer is not None:
        return model, optimizer
    return model
