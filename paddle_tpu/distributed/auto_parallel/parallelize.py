"""``paddle.distributed.parallelize`` — plan-driven model parallelization.

Parity: python/paddle/distributed/auto_parallel/intermediate/ (parallelize
with dp/mp/pp configs, ColWiseParallel/RowWiseParallel plans). TPU-native
design: a plan entry shards the matched layer's parameters over the mesh's
``mp`` axis with jax NamedShardings — XLA inserts the TP collectives; dp
config shards the batch (callers place inputs); pp config is routed to the
pipeline engine which has its own schedule machinery.
"""

from __future__ import annotations

import fnmatch
from typing import Dict, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn.layer import Layer
from ..auto_parallel_api import ProcessMesh, get_mesh

__all__ = ["parallelize", "ColWiseParallel", "RowWiseParallel",
           "PrepareLayerInput", "PrepareLayerOutput",
           "SequenceParallelBegin", "SequenceParallelEnd"]


class _Plan:
    def apply(self, layer: Layer, mesh: ProcessMesh, axis: str) -> None:
        raise NotImplementedError


class ColWiseParallel(_Plan):
    """Shard the output dimension of a Linear/Embedding weight over ``mp``:
    weight (in, out) -> P(None, 'mp'); bias (out,) -> P('mp')."""

    def __init__(self, gather_output: bool = False):
        self.gather_output = gather_output

    def apply(self, layer, mesh, axis):
        w = getattr(layer, "weight", None)
        if w is not None:
            spec = [None] * (w._data.ndim - 1) + [axis]
            w._set_data(jax.device_put(
                w._data, NamedSharding(mesh.jax_mesh, P(*spec))))
        b = getattr(layer, "bias", None)
        if b is not None:
            b._set_data(jax.device_put(
                b._data, NamedSharding(mesh.jax_mesh, P(axis))))


class RowWiseParallel(_Plan):
    """Shard the input dimension over ``mp``: weight (in, out) ->
    P('mp', None); bias replicated."""

    def __init__(self, is_input_parallel: bool = True):
        self.is_input_parallel = is_input_parallel

    def apply(self, layer, mesh, axis):
        w = getattr(layer, "weight", None)
        if w is not None:
            spec = [axis] + [None] * (w._data.ndim - 1)
            w._set_data(jax.device_put(
                w._data, NamedSharding(mesh.jax_mesh, P(*spec))))
        b = getattr(layer, "bias", None)
        if b is not None:
            b._set_data(jax.device_put(
                b._data, NamedSharding(mesh.jax_mesh, P())))


class PrepareLayerInput(_Plan):
    def __init__(self, fn=None):
        self.fn = fn

    def apply(self, layer, mesh, axis):
        if self.fn is not None:
            layer.register_forward_pre_hook(
                lambda l, inp: self.fn(inp, process_mesh=mesh))


class PrepareLayerOutput(_Plan):
    def __init__(self, fn=None):
        self.fn = fn

    def apply(self, layer, mesh, axis):
        if self.fn is not None:
            layer.register_forward_post_hook(
                lambda l, inp, out: self.fn(out, process_mesh=mesh))


class SequenceParallelBegin(_Plan):
    """Marker plans: sequence-parallel scatter/gather boundaries are sharding
    constraints under jit; eager keeps the layer untouched."""

    def apply(self, layer, mesh, axis):
        pass


class SequenceParallelEnd(SequenceParallelBegin):
    pass


def _match_layers(model: Layer, pattern: str):
    for name, sub in model.named_sublayers():
        if fnmatch.fnmatch(name, pattern):
            yield name, sub


def parallelize(model: Layer, optimizer=None,
                mesh: Optional[ProcessMesh] = None,
                config: Optional[Dict] = None):
    """Apply a hybrid-parallel ``config`` to ``model`` (reference:
    paddle.distributed.parallelize).

    config = {"mp_config": {"parallelize_plan": {"pattern": Plan}},
              "dp_config": {"sharding_level": 0|1|2|3},
              "pp_config": {...}}
    """
    config = config or {}
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("parallelize needs a mesh: pass mesh= or call "
                         "paddle.distributed.set_mesh(...) first")
    mp_axis = "mp" if "mp" in mesh.dim_names else mesh.dim_names[-1]

    mp_cfg = config.get("mp_config") or {}
    plan = mp_cfg.get("parallelize_plan") or {}
    for pattern, plan_obj in plan.items():
        plans = plan_obj if isinstance(plan_obj, (list, tuple)) else [plan_obj]
        for _, sub in _match_layers(model, pattern):
            for p in plans:
                p.apply(sub, mesh, mp_axis)

    dp_cfg = config.get("dp_config") or {}
    level = int(dp_cfg.get("sharding_level", 0) or 0)
    if optimizer is not None and level:
        from ..sharding import DygraphShardingOptimizer
        optimizer = DygraphShardingOptimizer(optimizer, stage=level)
    if optimizer is not None:
        return model, optimizer
    return model
