"""DataParallel.

Parity surface: python/paddle/parallel.py ``paddle.DataParallel`` + the C++
EagerReducer (upstream paddle/fluid/distributed/collective/reducer.cc —
bucketed, hook-triggered fused allreduce). TPU-native design: under
``to_static`` the batch is sharded over the dp axis and XLA inserts + fuses
the gradient all-reduces itself (reducer bucketing is obsolete — SURVEY.md
§5). Eagerly, ``apply_collective_grads`` averages grads with one psum per
parameter group over the dp axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer import Layer
from .topology import get_hybrid_communicate_group, global_mesh

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1, find_unused_parameters: bool = False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        return out

    def _dp_axis(self):
        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            return hcg.mesh, "dp"
        mesh = global_mesh()
        return mesh, mesh.axis_names[0]

    def shard_input(self, tensor: Tensor) -> Tensor:
        """Shard a global batch over the dp axis; XLA then computes per-shard
        grads and all-reduces them inside the compiled step."""
        mesh, axis = self._dp_axis()
        spec = P(axis, *([None] * (tensor._data.ndim - 1)))
        tensor._set_data(jax.device_put(tensor._data, NamedSharding(mesh, spec)))
        return tensor

    def apply_collective_grads(self) -> None:
        """Eager grad averaging (reducer parity). With sharded inputs the
        grads are already globally correct — this is for the manual path
        where each call site computed rank-local grads."""
        mesh, axis = self._dp_axis()
        g = int(mesh.shape[axis])
        if g == 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                # grads computed from a dp-sharded batch are partial sums per
                # shard only when the loss was a per-shard mean; XLA's psum
                # already ran if the input was sharded. Scale-normalize:
                p.grad._set_data(p.grad._data / 1.0)

    # delegate the Layer surface to the wrapped module
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()

    @property
    def _sub(self):
        return self._layers
