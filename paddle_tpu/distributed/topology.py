"""Process topology → jax device mesh.

Parity surface: python/paddle/distributed/fleet/base/topology.py
(``CommunicateTopology``, ``HybridCommunicateGroup`` — the 4-5D process
"mesh" of dp × pp × sharding × mp × sep built from comm groups). TPU-native
design: the topology IS a ``jax.sharding.Mesh`` with named axes; per-axis
"communication groups" are just axis names handed to collectives, and XLA
routes them over ICI. One ``HybridCommunicateGroup`` activates globally
(mirroring fleet's singleton hcg).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "ProcessGroup",
           "get_hybrid_communicate_group", "set_hybrid_communicate_group",
           "global_mesh", "new_group"]

# canonical axis order mirrors fleet's default hybrid order
_AXIS_ORDER = ("pp", "dp", "sharding", "sep", "mp")


class ProcessGroup:
    """A communication group = a mesh axis (or the trivial 1-axis world).

    Parity: the reference's ProcessGroup handle (upstream
    paddle/fluid/distributed/collective/process_group.h). ``axis_name``
    addresses collectives; ``ranks`` lists member positions along that axis.
    """

    _next_gid = itertools.count(1)  # 0 is the world group

    # gid -> group, weakly held (groups are created per call by the hcg
    # accessors — strong registry references would grow without bound and
    # outlive their meshes). gid 0 is RESERVED for the world group.
    import weakref as _weakref
    _registry: "ProcessGroup._weakref.WeakValueDictionary" = \
        _weakref.WeakValueDictionary()

    def __init__(self, mesh: Mesh, axis_name: Optional[str], ranks=None,
                 rank: int = 0):
        self.id = next(ProcessGroup._next_gid)
        ProcessGroup._registry[self.id] = self
        self.mesh = mesh
        self.axis_name = axis_name
        self.nranks = int(mesh.shape[axis_name]) if axis_name else 1
        self.ranks = list(ranks) if ranks is not None else list(range(self.nranks))
        self.rank = rank

    @property
    def world_size(self) -> int:
        return self.nranks

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"ProcessGroup(axis={self.axis_name}, nranks={self.nranks})"


class CommunicateTopology:
    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe",
                                                            "sharding", "sep", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self._world = int(np.prod(self._dims))

    def get_hybrid_group_names(self) -> List[str]:
        return self._names

    def get_dim(self, name: str) -> int:
        return self._dims[self._names.index(name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world

    def get_rank(self, **kwargs) -> int:
        coord = [kwargs[n] for n in self._names]
        return int(np.ravel_multi_index(coord, self._dims))

    def get_coord(self, rank: int):
        return tuple(int(c) for c in np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._names.index(axis_name)
        ranks = []
        for r in range(self._world):
            if self.get_coord(r)[axis] == index:
                ranks.append(r)
        return ranks

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        axis = self._names.index(axis_name)
        groups: Dict[tuple, List[int]] = {}
        for r in range(self._world):
            c = list(self.get_coord(r))
            c[axis] = -1
            groups.setdefault(tuple(c), []).append(r)
        return list(groups.values())


class HybridCommunicateGroup:
    """Builds the hybrid mesh. Axis names on the jax Mesh: dp, pp, sharding,
    sep, mp (only axes with degree > 1 when ``squeeze`` is True)."""

    def __init__(self, dp_degree: int = 1, mp_degree: int = 1,
                 pp_degree: int = 1, sharding_degree: int = 1,
                 sep_degree: int = 1, order: Optional[Sequence[str]] = None,
                 devices=None):
        self._degrees = {"dp": dp_degree, "mp": mp_degree, "pp": pp_degree,
                         "sharding": sharding_degree, "sep": sep_degree}
        order = tuple(order) if order else _AXIS_ORDER
        self._order = order
        devices = list(devices) if devices is not None else jax.devices()
        total = int(np.prod(list(self._degrees.values())))
        if total > len(devices):
            raise ValueError(
                f"hybrid degrees {self._degrees} need {total} devices, "
                f"only {len(devices)} available")
        devices = devices[:total]
        shape = [self._degrees[a] for a in order]
        self.mesh = Mesh(np.array(devices).reshape(shape), order)
        set_hybrid_communicate_group(self)
        self._topology = CommunicateTopology(
            hybrid_group_names=list(order), dims=shape)

    # --- parity getters ------------------------------------------------------
    @property
    def topology(self) -> CommunicateTopology:
        return self._topology

    def _group(self, axis: str) -> ProcessGroup:
        return ProcessGroup(self.mesh, axis if self._degrees[axis] > 1 else axis)

    def get_parallel_mode(self) -> str:
        if self._degrees["pp"] > 1:
            return "pipeline"
        if self._degrees["sharding"] > 1:
            return "sharding_parallel"
        if self._degrees["mp"] > 1:
            return "model"
        return "data"

    # world sizes
    def get_data_parallel_world_size(self) -> int:
        return self._degrees["dp"]

    def get_model_parallel_world_size(self) -> int:
        return self._degrees["mp"]

    def get_pipe_parallel_world_size(self) -> int:
        return self._degrees["pp"]

    def get_sharding_parallel_world_size(self) -> int:
        return self._degrees["sharding"]

    def get_sep_parallel_world_size(self) -> int:
        return self._degrees["sep"]

    # groups (mesh-axis handles)
    def get_data_parallel_group(self) -> ProcessGroup:
        return self._group("dp")

    def get_model_parallel_group(self) -> ProcessGroup:
        return self._group("mp")

    def get_pipe_parallel_group(self) -> ProcessGroup:
        return self._group("pp")

    def get_sharding_parallel_group(self) -> ProcessGroup:
        return self._group("sharding")

    def get_sep_parallel_group(self) -> ProcessGroup:
        return self._group("sep")

    def get_check_parallel_group(self, *a) -> ProcessGroup:
        return self._group("mp")

    # ranks: single-process SPMD has no per-process coordinate; expose 0 for
    # parity (mesh positions replace ranks inside compiled programs)
    def get_data_parallel_rank(self) -> int:
        return 0

    def get_model_parallel_rank(self) -> int:
        return 0

    def get_stage_id(self) -> int:
        return 0

    def get_sharding_parallel_rank(self) -> int:
        return 0

    def get_global_rank(self) -> int:
        from .env import get_rank
        return get_rank()


_hcg: Optional[HybridCommunicateGroup] = None
_default_mesh: Optional[Mesh] = None


_topology_epoch = 0


def topology_epoch() -> int:
    """Monotonic counter bumped on every hybrid-topology (re)set — cache
    keys derived from the live topology use this instead of object ids
    (CPython id reuse would alias a dead mesh's cache entries)."""
    return _topology_epoch


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup) -> None:
    global _hcg, _topology_epoch
    _hcg = hcg
    _topology_epoch += 1
    # purge topology-scoped caches EAGERLY: dist.split's cached layers hold
    # registered state tensors committed to the OLD mesh — left alive, they
    # ride into every later to_static state signature and collide with the
    # new mesh's device set (the lazy next-call purge is not enough when
    # split is never called again)
    try:
        from .comm import _SPLIT_LAYERS
        _SPLIT_LAYERS.clear()
    except ImportError:  # pragma: no cover - circular-import guard
        pass


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def _ensure_default_topology() -> None:
    """Default 1D dp mesh over all local devices (init_parallel_env path)."""
    global _default_mesh
    if _hcg is None and _default_mesh is None:
        devs = jax.devices()
        _default_mesh = Mesh(np.array(devs), ("dp",))


def global_mesh() -> Mesh:
    """The active mesh: the hybrid mesh if fleet initialized one, else the
    default dp mesh over all devices."""
    if _hcg is not None:
        return _hcg.mesh
    _ensure_default_topology()
    return _default_mesh


def new_group(ranks=None, backend=None, timeout=None) -> ProcessGroup:
    """Parity: paddle.distributed.new_group. Groups are mesh-axis handles;
    a rank-list subset of the world maps onto the dp axis of the active
    mesh (arbitrary subsets would need their own sub-mesh — supported for the
    common all-ranks case)."""
    mesh = global_mesh()
    axis = mesh.axis_names[0]
    return ProcessGroup(mesh, axis, ranks=ranks)
