"""``paddle.io``: datasets, samplers, DataLoader.

Parity surface: python/paddle/io/ (Dataset, IterableDataset, DataLoader with
worker processes, BatchSampler, DistributedBatchSampler). TPU-native notes:
host->device transfer happens once per batch via ``to_tensor`` (device_put);
a background thread prefetches batches (the analogue of the reference's C++
BlockingQueue double-buffering); multiprocess workers use the standard
``multiprocessing`` pool since jax arrays are produced only at collate time.
"""

from __future__ import annotations

import itertools
import math
import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from .. import observability as _obs
from ..core.random import default_generator
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Tensor]):
        self.tensors = [t if isinstance(t, Tensor) else to_tensor(t) for t in tensors]
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def _host_rng(generator=None):
    """Host-side numpy RNG seeded from the framework generator, so that
    ``paddle.seed`` makes shuffle order reproducible (upstream: samplers draw
    from the global phi Generator)."""
    gen = generator if generator is not None else default_generator
    key = np.asarray(gen.split_key(), dtype=np.uint64)
    return np.random.default_rng(key)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        total = len(dataset)
        lengths = [int(math.floor(total * l)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = _host_rng(generator).permutation(len(dataset)).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l]))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = _host_rng(self.generator)
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = _host_rng()
        idx = rng.choice(len(self.weights), self.num_samples,
                         replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (upstream:
    python/paddle/io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(b._data) for b in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.floating, np.integer)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


_TENSOR_TAG = "__pdtpu_tensor__"


def _encode_for_ipc(obj):
    """Tensors can't cross process boundaries as PJRT buffers; ship numpy."""
    if isinstance(obj, Tensor):
        return (_TENSOR_TAG, np.asarray(obj._data))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_encode_for_ipc(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _encode_for_ipc(v) for k, v in obj.items()}
    return obj


def _decode_from_ipc(obj):
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == _TENSOR_TAG:
        return to_tensor(obj[1])
    if isinstance(obj, (list, tuple)):
        return type(obj)(_decode_from_ipc(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _decode_from_ipc(v) for k, v in obj.items()}
    return obj


def _np_collate(batch):
    """Worker-side default collate: stacks to numpy so the worker process
    never touches a jax backend (the parent does the single device_put)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return (_TENSOR_TAG, np.stack([np.asarray(b._data) for b in batch]))
    if isinstance(sample, np.ndarray):
        return (_TENSOR_TAG, np.stack(batch))
    if isinstance(sample, (int, float, np.floating, np.integer)):
        return (_TENSOR_TAG, np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(_np_collate(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    return batch


def _worker_loop(dataset, index_queue, result_queue, collate_fn, init_fn,
                 worker_id, num_workers, iterable_mode, batch_size,
                 drop_last):
    """Body of one spawned worker process (upstream parity:
    python/paddle/io/dataloader/worker.py _worker_loop)."""
    global _worker_info
    try:
        # keep jax (and especially any TPU plugin) OUT of worker processes:
        # pin cpu before anything can query a backend
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            from .. import device as _device
            _device.force_platform("cpu")
        except Exception:
            pass  # device module import raced/failed in the fresh worker:
            #       the JAX_PLATFORMS env pin above already keeps jax on cpu
        _worker_info = _WorkerInfo(worker_id, num_workers, dataset)
        if init_fn is not None:
            init_fn(worker_id)
        if iterable_mode:
            try:
                it = iter(dataset)
                seq = worker_id
                while True:
                    batch = list(itertools.islice(it, batch_size))
                    if not batch or (len(batch) < batch_size and drop_last):
                        break
                    result_queue.put(
                        (seq, _encode_for_ipc(collate_fn(batch))))
                    seq += num_workers
            except Exception as e:
                result_queue.put(("error", (worker_id, repr(e))))
            result_queue.put(("done", worker_id))
            # wait for the shutdown token so the queue is drained cleanly
            while True:
                cmd = index_queue.get()
                if cmd is None:
                    break
        else:
            while True:
                cmd = index_queue.get()
                if cmd is None:
                    break
                epoch, seq, idx_batch = cmd
                try:
                    out = _encode_for_ipc(
                        collate_fn([dataset[i] for i in idx_batch]))
                    result_queue.put((epoch, seq, out))
                except Exception as e:  # ship the error, keep serving
                    result_queue.put((epoch, "error", (seq, repr(e))))
    except KeyboardInterrupt:
        pass  # parent is shutting down (Ctrl-C fans out to the process
        #       group): exit the worker loop without a traceback


class _WorkerPool:
    """N spawned workers fed by an index queue, drained in submit order."""

    def __init__(self, loader):
        import multiprocessing as mp

        self._loader = loader
        ctx = mp.get_context("spawn")
        self._index_queues = []
        n = loader.num_workers
        # bounded: gives iterable-mode workers backpressure (map mode is
        # already throttled by the in-flight window) + room for control
        # tokens
        self._result_queue = ctx.Queue(
            maxsize=max(2, loader.prefetch_factor) * n + n)
        user_collate = loader.collate_fn is not default_collate_fn
        collate = loader.collate_fn if user_collate else _np_collate
        self._procs = []
        self._epoch = 0  # stale-epoch filter: an early-broken epoch leaves
        #                  in-flight results that must not leak into the next
        # children must pin to cpu BEFORE they unpickle the dataset (a
        # dataset holding Tensors would otherwise initialize the parent's
        # real backend while deserializing Process args)
        import os
        prev_plat = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for w in range(n):
                iq = ctx.Queue()
                self._index_queues.append(iq)
                p = ctx.Process(
                    target=_worker_loop,
                    args=(loader.dataset, iq, self._result_queue, collate,
                          loader.worker_init_fn, w, n, loader._iterable_mode,
                          loader.batch_size, loader.drop_last),
                    daemon=True)
                p.start()
                self._procs.append(p)
        finally:
            if prev_plat is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = prev_plat

    def _get_result(self, timeout):
        """Blocking get with worker-liveness polling: a hard worker death
        (segfault/OOM-kill) must raise, not hang the trainer forever."""
        if _obs.enabled():
            try:  # queue depth BEFORE the take: how far ahead workers are
                _obs.set_gauge("dataloader.queue_depth",
                               self._result_queue.qsize())
            except NotImplementedError:
                pass  # macOS: mp.Queue.qsize is unimplemented
            with _obs.scoped_timer("dataloader.wait_seconds"):
                return self._get_result_impl(timeout)
        return self._get_result_impl(timeout)

    def _get_result_impl(self, timeout):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            poll = 5.0 if deadline is None else max(
                0.01, min(5.0, deadline - time.monotonic()))
            try:
                return self._result_queue.get(timeout=poll)
            except queue.Empty:
                if deadline is not None and time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"DataLoader timed out after {timeout}s waiting for "
                        "a worker batch") from None
                dead = [w for w, p in enumerate(self._procs)
                        if not p.is_alive()]
                if dead:
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} died unexpectedly "
                        "(killed or crashed outside Python)")

    def run_epoch(self):
        loader = self._loader
        timeout = (loader.timeout
                   if loader.timeout and loader.timeout > 0 else None)
        if loader._iterable_mode:
            yield from self._run_iterable(timeout)
            return
        self._epoch += 1
        epoch = self._epoch
        indices = list(loader.batch_sampler)
        n_batches = len(indices)
        inflight_target = max(2, loader.prefetch_factor) * len(self._procs)
        next_submit = 0
        received = {}
        next_yield = 0
        while next_yield < n_batches:
            while (next_submit < n_batches
                   and next_submit - next_yield < inflight_target):
                self._index_queues[next_submit % len(self._procs)].put(
                    (epoch, next_submit, indices[next_submit]))
                next_submit += 1
            while next_yield in received:
                yield _decode_from_ipc(received.pop(next_yield))
                next_yield += 1
            if next_yield >= n_batches:
                break
            ep, tag, payload = self._get_result(timeout)
            if ep != epoch:
                continue  # stale result from an early-broken prior epoch
            if tag == "error":
                seq, msg = payload
                raise RuntimeError(
                    f"DataLoader worker failed on batch {seq}: {msg}")
            received[tag] = payload

    def _run_iterable(self, timeout):
        done = 0
        received = {}
        # workers stream (seq = worker_id + k*num_workers); yield in global
        # seq order so two epochs of the same dataset agree
        next_seq = 0
        while done < len(self._procs):
            if next_seq in received:
                yield _decode_from_ipc(received.pop(next_seq))
                next_seq += 1
                continue
            tag, payload = self._get_result(timeout)
            if tag == "done":
                done += 1
                continue
            if tag == "error":
                seq, msg = payload
                raise RuntimeError(f"DataLoader worker failed: {msg}")
            received[tag] = payload
        # stragglers: some seq numbers never arrive (a worker exhausted
        # early); yield the rest in ascending order
        for seq in sorted(received):
            yield _decode_from_ipc(received.pop(seq))

    def shutdown(self):
        for iq in self._index_queues:
            try:
                iq.put(None)
            except Exception:
                pass  # queue torn down by a dead worker: join/terminate
                #       below still reaps the process
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._procs = []

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass  # interpreter teardown: queues/processes may be half-dead
            #       and shutdown is best-effort by contract


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif not self._iterable_mode and batch_size is not None:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        else:
            self.batch_sampler = None
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.timeout = timeout
        self._pool = None
        # resumable-iteration cursor (state_dict/load_state_dict): the
        # LAST-started iteration owns these — concurrent iterators over
        # one DataLoader are outside the resume contract
        self._sd_epochs = 0        # completed full iterations
        self._sd_batch = 0         # batches handed out in the live iteration
        self._sd_in_epoch = False
        self._sd_epoch_rng = None  # generator key at iteration start
        self._sd_token = None      # cursor owner (the live iteration)
        self._resume = None        # pending load_state_dict payload

    def __del__(self):
        if self._pool is not None:
            self._pool.shutdown()

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("DataLoader over an IterableDataset has no length")

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        resume, self._resume = self._resume, None
        resuming = (resume is not None and resume.get("in_epoch")
                    and int(resume.get("batch", 0)) > 0)
        mode = ("resume" if resuming
                else "workers" if self.num_workers and self.num_workers > 0
                else "buffered" if self.use_buffer_reader else "sync")
        # cursor bookkeeping: the key snapshot is taken BEFORE the sampler
        # can split it, so a resume can replay this epoch's shuffle draw
        self._sd_epoch_rng = self._rng_snapshot()
        if resuming:
            self._sd_epochs = int(resume.get("epochs_completed", 0))
            self._sd_batch = int(resume.get("batch", 0))
            if resume.get("epoch_rng") is not None:
                self._sd_epoch_rng = list(resume["epoch_rng"])
            inner = self._resume_iter(resume)
        else:
            if resume is not None:
                self._sd_epochs = int(resume.get("epochs_completed", 0))
            self._sd_batch = 0
            inner = self._iter_impl()
        self._sd_in_epoch = True
        # ownership token: an ABANDONED iterator's deferred finally (it
        # runs at GC time) must not clobber the cursor of a newer live
        # iteration — the restart path abandons the faulted epoch's
        # iterator and immediately starts the resumed one
        token = object()
        self._sd_token = token
        finished = False
        try:
            for batch in inner:
                if self._sd_token is token:
                    self._sd_batch += 1
                _obs.inc("dataloader.batches_total", mode=mode)
                yield batch
            finished = True
        finally:
            if self._sd_token is token:
                self._sd_in_epoch = False
                if finished:
                    self._sd_epochs += 1
                    self._sd_batch = 0

    # -- resumable iteration state (PR 10) ----------------------------------
    @staticmethod
    def _rng_snapshot():
        """Flat uint32 view of the framework generator key (None when the
        key is not host-readable, e.g. inside a trace)."""
        try:
            arr = np.asarray(default_generator.state._data)
        except Exception:
            return None
        return [int(x) for x in arr.ravel().tolist()]

    def state_dict(self):
        """Resumable iteration position: completed epochs, the batch cursor
        of the live iteration, and the shuffle-generator key at its start.
        JSON-serializable; pair with :meth:`load_state_dict` to resume
        mid-epoch with the exact remaining batches (same shuffle order)."""
        return {
            "version": 1,
            "epochs_completed": int(self._sd_epochs),
            "batch": int(self._sd_batch) if self._sd_in_epoch else 0,
            "in_epoch": bool(self._sd_in_epoch),
            "epoch_rng": (None if self._sd_epoch_rng is None
                          else list(self._sd_epoch_rng)),
        }

    def load_state_dict(self, state) -> None:
        """Schedule a resume: the NEXT ``iter(loader)`` replays the
        interrupted epoch's shuffle draw from the recorded generator state,
        skips the already-consumed batches, and yields the remainder —
        leaving the global generator exactly as it was (rng-neutral, so a
        caller restoring its own RNG snapshot afterwards stays bitwise
        reproducible). Map-style datasets skip on indices (no sample is
        loaded or collated twice); iterable datasets re-consume the skipped
        prefix (no random access). The resumed epoch runs on the in-process
        path even when ``num_workers > 0``; worker pools re-engage on the
        following epoch."""
        if not isinstance(state, dict) or "version" not in state:
            raise ValueError("not a DataLoader state_dict")
        if int(state["version"]) != 1:
            raise ValueError(
                f"unsupported DataLoader state_dict version "
                f"{state['version']!r}")
        self._resume = dict(state)
        self._sd_epochs = int(state.get("epochs_completed", 0))
        self._sd_batch = 0
        self._sd_in_epoch = False

    def _resume_iter(self, resume):
        """Rebuild the interrupted iteration (see :meth:`load_state_dict`)."""
        import jax.numpy as jnp

        skip = int(resume.get("batch", 0))
        saved = resume.get("epoch_rng")
        if not self._iterable_mode and self.batch_sampler is not None:
            if saved is not None:
                prev = self._rng_snapshot()
                default_generator.set_state(
                    jnp.asarray(np.asarray(saved, dtype=np.uint32)))
                try:
                    # the epoch's sampler split is replayed eagerly HERE so
                    # the generator can be restored before anything else
                    # (prefetch threads included) touches it
                    batches = list(self.batch_sampler)
                finally:
                    if prev is not None:
                        default_generator.set_state(
                            jnp.asarray(np.asarray(prev, dtype=np.uint32)))
            else:
                batches = list(self.batch_sampler)

            def _gen():
                for idx_batch in batches[skip:]:
                    yield self.collate_fn(
                        [self.dataset[i] for i in idx_batch])

            src = _gen()
        else:
            src = itertools.islice(self._iter_batches(), skip, None)
        if self.use_buffer_reader:
            return self._thread_prefetch(src)
        return src

    def _iter_impl(self):
        if self.num_workers and self.num_workers > 0:
            pool = self._pool
            if pool is None:
                pool = _WorkerPool(self)
                # iterable workers exhaust their stream once; a persistent
                # pool would hang the next epoch — always rebuild for them
                if self.persistent_workers and not self._iterable_mode:
                    self._pool = pool
            try:
                yield from pool.run_epoch()
            finally:
                if pool is not self._pool:
                    pool.shutdown()
            return
        if self.use_buffer_reader:
            yield from self._thread_prefetch(self._iter_batches())
        else:
            yield from self._iter_batches()

    def _thread_prefetch(self, gen):
        """Background-thread double buffering: the native C++ BlockingQueue
        (paddle_tpu/_native) when available — the analogue of the reference's
        C++ BlockingQueue DataLoader feed — else a Python queue."""
        from .. import _native

        if _native.available():
            yield from self._native_prefetch(gen)
            return
        q: "queue.Queue" = queue.Queue(maxsize=max(2, self.prefetch_factor))
        sentinel = object()
        err: List[BaseException] = []

        def worker():
            try:
                for item in gen:
                    q.put(item)
            except BaseException as e:  # noqa: BLE001
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            if _obs.enabled():
                # depth before the take = how far ahead the prefetcher is;
                # wait time = how long the trainer starved
                _obs.set_gauge("dataloader.queue_depth", q.qsize())
                with _obs.scoped_timer("dataloader.wait_seconds"):
                    item = q.get()
            else:
                item = q.get()
            if item is sentinel:
                break
            yield item
        if err:
            raise err[0]

    def _native_prefetch(self, gen):
        from .. import _native

        q = _native.BlockingQueue(max(2, self.prefetch_factor))
        err: List[BaseException] = []

        def worker():
            try:
                for item in gen:
                    if not q.push(item):  # queue closed by consumer
                        return
            except BaseException as e:  # noqa: BLE001
                err.append(e)
            finally:
                q.close()

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                if _obs.enabled():
                    with _obs.scoped_timer("dataloader.wait_seconds"):
                        item = q.pop()
                else:
                    item = q.pop()
                if item is _native.BlockingQueue.CLOSED:
                    break
                yield item
        finally:
            q.close()
        if err:
            raise err[0]


class SubsetRandomSampler(Sampler):
    """Sample the given indices in random order (reference:
    paddle.io.SubsetRandomSampler)."""

    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        import numpy as np

        from ..core.random import default_generator
        import jax

        key = default_generator.split_key()
        perm = np.asarray(jax.random.permutation(key, len(self.indices)))
        return iter([self.indices[int(i)] for i in perm])

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    """Concatenation of map-style datasets (reference: io.ConcatDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        n = len(self)
        if idx < 0:
            if idx < -n:
                raise IndexError(
                    f"index {idx} out of range for ConcatDataset of "
                    f"length {n}")
            idx += n
        elif idx >= n:
            raise IndexError(
                f"index {idx} out of range for ConcatDataset of length {n}")
        import bisect
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]
