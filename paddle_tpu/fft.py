"""``paddle.fft`` — discrete Fourier transform family.

Parity surface: upstream python/paddle/fft.py (backed by
paddle/phi/kernels/*/fft_*). On TPU every transform is one jnp.fft call
dispatched through ``apply``: XLA lowers to its native FFT HLO and jax
provides the vjp, so the whole family is differentiable for free.

Signature conventions follow paddle: 1-D transforms take ``(x, n, axis,
norm)``; N-D transforms take ``(x, s, axes, norm)``; ``norm`` is one of
"backward" (default), "forward", "ortho".
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor, apply
from .ops._helpers import ensure_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "forward", "ortho")


def _check_norm(norm):
    if norm is None:
        return "backward"
    if norm not in _NORMS:
        raise ValueError(
            f"norm should be one of {_NORMS}, but got '{norm}'")
    return norm


def _make_1d(name, jfn, real_in=False):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        norm_ = _check_norm(norm)
        x = ensure_tensor(x)

        if real_in and jnp.iscomplexobj(x._data):
            raise TypeError(
                f"{name_} only supports real input, but got "
                f"{x._data.dtype}; use fft/fftn for complex input")

        def f(a):
            return jfn(a, n=n, axis=axis, norm=norm_)

        return apply(name_, f, x)
    name_ = name
    op.__name__ = name
    return op


def _make_nd(name, jfn, real_in=False):
    def op(x, s=None, axes=None, norm="backward", name=None):
        norm_ = _check_norm(norm)
        x = ensure_tensor(x)
        s_ = tuple(int(v) for v in s) if s is not None else None
        axes_ = tuple(int(v) for v in axes) if axes is not None else None
        if real_in and jnp.iscomplexobj(x._data):
            raise TypeError(
                f"{name_} only supports real input, but got "
                f"{x._data.dtype}; use fft/fftn for complex input")

        def f(a):
            return jfn(a, s=s_, axes=axes_, norm=norm_)

        return apply(name_, f, x)
    name_ = name
    op.__name__ = name
    return op


def _make_2d(name, jfn, real_in=False):
    nd = _make_nd(name, jfn, real_in=real_in)

    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return nd(x, s=s, axes=axes, norm=norm)
    op.__name__ = name
    return op


fft = _make_1d("fft", jnp.fft.fft)
ifft = _make_1d("ifft", jnp.fft.ifft)
rfft = _make_1d("rfft", jnp.fft.rfft, real_in=True)
irfft = _make_1d("irfft", jnp.fft.irfft)
hfft = _make_1d("hfft", jnp.fft.hfft)
ihfft = _make_1d("ihfft", jnp.fft.ihfft, real_in=True)

fft2 = _make_2d("fft2", jnp.fft.fftn)
ifft2 = _make_2d("ifft2", jnp.fft.ifftn)
rfft2 = _make_2d("rfft2", jnp.fft.rfftn, real_in=True)
irfft2 = _make_2d("irfft2", jnp.fft.irfftn)

fftn = _make_nd("fftn", jnp.fft.fftn)
ifftn = _make_nd("ifftn", jnp.fft.ifftn)
rfftn = _make_nd("rfftn", jnp.fft.rfftn, real_in=True)
irfftn = _make_nd("irfftn", jnp.fft.irfftn)


def _hfftn_impl(a, s=None, axes=None, norm="backward"):
    # hermitian-input N-D transform: conjugate-reverse trick over irfftn,
    # matching numpy.fft.hfft generalized to N dims (last axis hermitian).
    if axes is None:
        # numpy/paddle convention: with s given, transform the trailing
        # len(s) axes; otherwise all axes
        axes = (tuple(range(a.ndim)) if s is None
                else tuple(range(a.ndim - len(s), a.ndim)))
    axes = tuple(ax % a.ndim for ax in axes)
    inv_norm = {"backward": "forward", "forward": "backward",
                "ortho": "ortho"}[norm]
    if s is not None:
        n_last = s[-1]
    else:
        n_last = 2 * (a.shape[axes[-1]] - 1)
    full_s = (tuple(s[:-1]) if s is not None
              else tuple(a.shape[ax] for ax in axes[:-1])) + (n_last,)
    return jnp.fft.irfftn(jnp.conj(a), s=full_s, axes=axes, norm=inv_norm)


def _ihfftn_impl(a, s=None, axes=None, norm="backward"):
    inv_norm = {"backward": "forward", "forward": "backward",
                "ortho": "ortho"}[norm]
    return jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes, norm=inv_norm))


hfftn = _make_nd("hfftn", _hfftn_impl)
ihfftn = _make_nd("ihfftn", _ihfftn_impl, real_in=True)
hfft2 = _make_2d("hfft2", _hfftn_impl)
ihfft2 = _make_2d("ihfft2", _ihfftn_impl, real_in=True)


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(jnp.dtype(np.dtype(dtype)))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(jnp.dtype(np.dtype(dtype)))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    x = ensure_tensor(x)
    axes_ = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes_), x)


def ifftshift(x, axes=None, name=None):
    x = ensure_tensor(x)
    axes_ = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes_), x)
