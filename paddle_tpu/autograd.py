"""``paddle.autograd`` namespace: backward, grad, PyLayer, hooks.

Parity surface: python/paddle/autograd/ (+ the C++ egr::Backward engine it
fronts — see core/autograd.py for the TPU-native tape).
"""

from __future__ import annotations

from typing import Any

import jax

from .core.autograd import GradNode, backward, grad  # noqa: F401
from .core.tensor import Tensor
from .core.tracing import no_grad, set_grad_enabled  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "set_grad_enabled", "PyLayer",
           "PyLayerContext"]


class PyLayerContext:
    """Context passed to PyLayer forward/backward (parity:
    paddle.autograd.PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """User-defined differentiable op (parity: paddle.autograd.PyLayer).

    Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)`` static
    methods; invoke via ``apply``. The backward is stitched onto the tape as a
    GradNode whose vjp calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        from .core.tracing import grad_enabled
        needs_grad = grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if needs_grad:
            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                ct_tensors = [Tensor(c) for c in cts]
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                gins = gin if isinstance(gin, (tuple, list)) else (gin,)
                return tuple(g._data if isinstance(g, Tensor) else g for g in gins)

            node = GradNode(cls.__name__, vjp_fn, tensor_inputs, len(outs),
                            tuple((o._data.shape, o._data.dtype) for o in outs))
            for i, o in enumerate(outs):
                o.stop_gradient = False
                o._grad_node = node
                o._grad_index = i
        return out if multi else outs[0]
