"""``paddle.autograd`` namespace: backward, grad, PyLayer, hooks.

Parity surface: python/paddle/autograd/ (+ the C++ egr::Backward engine it
fronts — see core/autograd.py for the TPU-native tape).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from .core.autograd import GradNode, backward, grad  # noqa: F401
from .core.tensor import Tensor
from .core.tracing import no_grad, set_grad_enabled  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "set_grad_enabled", "PyLayer",
           "PyLayerContext", "jacobian", "hessian", "Jacobian", "Hessian",
           "jvp", "vjp"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _wrap_func(func):
    """Lift a Tensor->Tensor function to a pure jax-array function."""
    def pure(*arrays):
        with no_grad():
            out = func(*[Tensor(a) for a in arrays])
        if isinstance(out, (tuple, list)):
            return tuple(_unwrap(o) for o in out)
        return _unwrap(out)
    return pure


def jacobian(ys, xs, batch_axis=None):
    """Full Jacobian (parity: paddle.autograd.jacobian).

    Two call styles:
    - ``jacobian(ys, xs)`` where ``ys`` was computed on the eager tape from
      ``xs`` (``xs.stop_gradient == False``) — evaluated by running the tape
      backward once per output element with a one-hot cotangent.
    - ``jacobian(func, xs)`` with a callable — evaluated with ``jax.jacrev``
      on the lifted pure function (preferred: one trace, XLA-fused).

    Returns Tensor(s) of shape ``(*ys.shape, *xs.shape)`` per input.
    """
    import jax.numpy as jnp

    if batch_axis is not None:
        raise NotImplementedError(
            "batch_axis is not supported yet; vmap the function and call "
            "jacobian per sample")
    single = not isinstance(xs, (tuple, list))
    xs_list = [xs] if single else list(xs)

    if callable(ys) and not isinstance(ys, Tensor):
        jac = jax.jacrev(_wrap_func(ys), argnums=tuple(range(len(xs_list))))
        out = jac(*[_unwrap(x) for x in xs_list])
        res = [Tensor(o) for o in out]
        return res[0] if single else res

    from .core.autograd import grad as _grad
    ys_t = ys if isinstance(ys, Tensor) else Tensor(ys)
    n_out = int(np.prod(ys_t.shape)) if ys_t.ndim else 1
    rows = []  # one backward pass per output element
    for i in range(n_out):
        ct = jnp.zeros((n_out,), ys_t._data.dtype).at[i].set(1).reshape(
            ys_t._data.shape if ys_t.ndim else ())
        gs = _grad([ys_t], xs_list, grad_outputs=[Tensor(ct)],
                   retain_graph=True, allow_unused=True)
        rows.append([g._data if g is not None
                     else jnp.zeros(x._data.shape, ys_t._data.dtype)
                     for g, x in zip(gs, xs_list)])
    res = []
    for j, x in enumerate(xs_list):
        stacked = jnp.stack([r[j] for r in rows]).reshape(
            tuple(ys_t.shape) + tuple(x.shape))
        res.append(Tensor(stacked))
    return res[0] if single else res


def hessian(func, xs, batch_axis=None):
    """Hessian of a scalar-valued ``func`` at ``xs`` (parity:
    paddle.autograd.hessian / paddle.incubate.autograd.Hessian).

    The eager tape does not support ``create_graph`` (double backward), so the
    Tensor-form ``hessian(ys, xs)`` is not available — pass the callable; it
    is evaluated with ``jax.hessian`` on the lifted pure function.
    """
    if batch_axis is not None:
        raise NotImplementedError(
            "batch_axis is not supported yet; vmap the function and call "
            "hessian per sample")
    if isinstance(func, Tensor):
        raise NotImplementedError(
            "hessian(ys, xs) over the eager tape needs double-backward; pass "
            "the function instead: paddle.autograd.hessian(func, xs)")
    single = not isinstance(xs, (tuple, list))
    xs_list = [xs] if single else list(xs)
    h = jax.hessian(_wrap_func(func), argnums=tuple(range(len(xs_list))))
    out = h(*[_unwrap(x) for x in xs_list])
    if single:
        return Tensor(out[0][0])
    return [[Tensor(b) for b in row] for row in out]


class Jacobian:
    """Functional lazy Jacobian (parity: paddle.incubate.autograd.Jacobian).
    With a sequence of inputs, ``self[i]`` is the Jacobian w.r.t. input i."""

    def __init__(self, func, xs, is_batched=False):
        self._val = jacobian(func, xs)

    def __getitem__(self, idx):
        return self._val[idx]

    @property
    def shape(self):
        if isinstance(self._val, (list, tuple)):
            return [v.shape for v in self._val]
        return self._val.shape


class Hessian(Jacobian):
    """Functional lazy Hessian (parity: paddle.incubate.autograd.Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        self._val = hessian(func, xs)


def _wrap_out(out):
    if isinstance(out, tuple):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


def vjp(func, xs, v=None):
    """(outputs, vjp_result) — parity: paddle.incubate.autograd.vjp.
    Multi-output funcs are supported; default cotangent is ones per output."""
    import jax.numpy as jnp
    single = not isinstance(xs, (tuple, list))
    xs_list = [xs] if single else list(xs)
    out, pull = jax.vjp(_wrap_func(func), *[_unwrap(x) for x in xs_list])
    if v is None:
        v_arr = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_arr = jax.tree_util.tree_map(
            _unwrap, tuple(v) if isinstance(v, (tuple, list)) else v,
            is_leaf=lambda x: isinstance(x, Tensor))
    grads = pull(v_arr)
    gres = Tensor(grads[0]) if single else [Tensor(g) for g in grads]
    return _wrap_out(out), gres


def jvp(func, xs, v=None):
    """(outputs, jvp_result) — parity: paddle.incubate.autograd.jvp.
    Multi-output funcs are supported (tangent returned per output)."""
    import jax.numpy as jnp
    single = not isinstance(xs, (tuple, list))
    xs_list = [_unwrap(x) for x in ([xs] if single else list(xs))]
    if v is None:
        vs = [jnp.ones_like(x) for x in xs_list]
    else:
        vs = [_unwrap(t) for t in ([v] if single else list(v))]
    out, tangent = jax.jvp(_wrap_func(func), tuple(xs_list), tuple(vs))
    return _wrap_out(out), _wrap_out(tangent)


class PyLayerContext:
    """Context passed to PyLayer forward/backward (parity:
    paddle.autograd.PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        # method, not property: the reference API spells it
        # ``ctx.saved_tensor()`` (python/paddle/autograd/py_layer.py), and
        # reference PyLayer code calls it — a property here broke that code
        # with "tuple is not callable"
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """User-defined differentiable op (parity: paddle.autograd.PyLayer).

    Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)`` static
    methods; invoke via ``apply``. The backward is stitched onto the tape as a
    GradNode whose vjp calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        from .core.tracing import grad_enabled
        needs_grad = grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if needs_grad:
            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                ct_tensors = [Tensor(c) for c in cts]
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                gins = gin if isinstance(gin, (tuple, list)) else (gin,)
                return tuple(g._data if isinstance(g, Tensor) else g for g in gins)

            node = GradNode(cls.__name__, vjp_fn, tensor_inputs, len(outs),
                            tuple((o._data.shape, o._data.dtype) for o in outs))
            for i, o in enumerate(outs):
                o.stop_gradient = False
                o._grad_node = node
                o._grad_index = i
        return out if multi else outs[0]


import contextlib as _contextlib

_saved_hooks_stack = []


@_contextlib.contextmanager
def saved_tensors_hooks(pack_hook, unpack_hook):
    """Parity: paddle.autograd.saved_tensors_hooks. The eager tape stores
    residuals inside jax vjp closures (not as framework tensors), so
    pack/unpack cannot intercept them tensor-by-tensor; the supported
    memory-control path is fleet.utils.recompute. The context records the
    hooks so code probing for the API runs; a warning states the
    divergence."""
    import warnings

    warnings.warn(
        "saved_tensors_hooks: residuals live inside jax vjp closures on this "
        "runtime; hooks are recorded but not applied per-tensor. Use "
        "fleet.utils.recompute (activation checkpointing) for memory "
        "control.", stacklevel=2)
    _saved_hooks_stack.append((pack_hook, unpack_hook))
    try:
        yield
    finally:
        _saved_hooks_stack.pop()


def set_detect_anomaly(mode: bool) -> None:
    """Parity: anomaly detection — when on, backward() checks every produced
    gradient for NaN/Inf and raises naming the op. Single source of truth:
    the flag backward() reads in core.autograd."""
    from .core import autograd as _core_ad
    _core_ad._detect_anomaly = bool(mode)


def is_anomaly_enabled() -> bool:
    from .core import autograd as _core_ad
    return _core_ad._detect_anomaly
