"""Device / Place taxonomy.

Parity surface: ``phi::Place`` (upstream: paddle/phi/common/place.h) and
``paddle.device.set_device`` (python/paddle/device/__init__.py). TPU-native
design: a Place names a jax device; ``set_device`` selects the default
placement used by tensor factories; cross-place copies are ``jax.device_put``.
No DeviceContext/stream pool is needed — XLA/PJRT owns streams and events.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CUDAPlace", "CustomPlace",
    "XPUPlace", "MLUPlace", "IPUPlace", "CUDAPinnedPlace",
    "set_device", "get_device", "get_all_devices", "device_count",
    "is_compiled_with_cuda", "is_compiled_with_tpu", "current_place",
    "device_put", "force_platform", "force_platform_from_env",
]


class Place:
    """Identity of a physical device: (device_type, device_id)."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- mapping to jax ------------------------------------------------------
    def jax_device(self) -> jax.Device:
        devs = _devices_of_type(self.device_type)
        if not devs:
            raise RuntimeError(f"no {self.device_type!r} devices visible to jax")
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


def CPUPlace(device_id: int = 0) -> Place:
    return Place("cpu", device_id)


def TPUPlace(device_id: int = 0) -> Place:
    return Place("tpu", device_id)


def CUDAPlace(device_id: int = 0) -> Place:
    # Parity alias: there is no CUDA on TPU systems; accepted so reference
    # scripts run, mapped to the accelerator if present else CPU.
    return Place("tpu", device_id) if _accelerator_type() == "tpu" else Place("cpu", device_id)


def CustomPlace(device_type: str, device_id: int = 0) -> Place:
    return Place(device_type, device_id)


def XPUPlace(device_id: int = 0) -> Place:
    # Parity alias (Kunlun XPU in the reference): maps to the accelerator.
    return CUDAPlace(device_id)


def MLUPlace(device_id: int = 0) -> Place:
    return CUDAPlace(device_id)


def IPUPlace(device_id: int = 0) -> Place:
    return CUDAPlace(device_id)


def CUDAPinnedPlace() -> Place:
    # Pinned host memory: on TPU the host side is plain CPU memory (PJRT
    # stages transfers itself), so this is the cpu place.
    return Place("cpu", 0)


@functools.lru_cache(maxsize=None)
def _devices_of_type(device_type: str):
    try:
        all_devs = jax.devices()
    except RuntimeError:
        all_devs = []
    if device_type == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple(d for d in all_devs if d.platform == "cpu")
    # A TPU may surface as platform 'tpu' or (via tunnel) an experimental
    # platform; treat any non-cpu accelerator as the 'tpu' place.
    accel = tuple(d for d in all_devs if d.platform != "cpu")
    if device_type in ("tpu", "gpu", "xpu"):
        return accel
    return tuple(d for d in all_devs if d.platform == device_type)


@functools.lru_cache(maxsize=1)
def _accelerator_type() -> str:
    try:
        if any(d.platform != "cpu" for d in jax.devices()):
            return "tpu"
    except RuntimeError:
        pass  # backend probe failed (no TPU runtime reachable): cpu below
    return "cpu"


_current_place: Optional[Place] = None


def set_device(device: Union[str, Place]) -> Place:
    """``paddle.device.set_device('tpu')`` / ``('tpu:0')`` / ``('cpu')``."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    dev = device.lower()
    if dev in ("gpu", "cuda", "xpu"):
        dev = "tpu" if _accelerator_type() == "tpu" else "cpu"
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        _current_place = Place(kind, int(idx))
    else:
        _current_place = Place(dev, 0)
    _current_place.jax_device()  # validate eagerly
    return _current_place


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = Place(_accelerator_type(), 0)
    return _current_place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count(device_type: Optional[str] = None) -> int:
    return len(_devices_of_type(device_type or current_place().device_type))


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return _accelerator_type() == "tpu"


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # the graph compiler role is filled by XLA itself (SURVEY §2.5.7)
    return True


def is_compiled_with_distribute() -> bool:
    return True


def is_compiled_with_custom_device(device_type: str = "") -> bool:
    # any non-cpu PJRT backend is a "custom device" in reference terms
    return _accelerator_type() != "cpu"


def default_jax_device() -> jax.Device:
    return current_place().jax_device()


def device_put(x, place: Union[str, Place, jax.Device, None] = None):
    """The sanctioned single-device transfer: ``jax.device_put`` with the
    target resolved through the Place taxonomy (``None`` → the current
    default device). Every non-distributed transfer in the framework
    routes through here or through ``core/fallback.py`` — enforced by the
    ``device-access`` lint rule; the distributed layer's mesh-sharded
    ``device_put(x, NamedSharding(...))`` calls are a different API and
    stay in that layer (baselined)."""
    if place is None:
        dev = default_jax_device()
    elif isinstance(place, Place):
        dev = place.jax_device()
    elif isinstance(place, jax.Device):
        dev = place
    else:
        dev = Place(*_parse_device_str(str(place).lower())).jax_device()
    return jax.device_put(x, dev)


# ---------------------------------------------------------------------------
# Device memory stats (parity: paddle.device.cuda.max_memory_allocated & co,
# backed by the allocator StatAllocator counters in the reference — here by
# PJRT per-device memory_stats(), which libtpu/XLA maintain natively).
# ---------------------------------------------------------------------------

def _memory_stats(device: Union[str, Place, None] = None) -> dict:
    if device is None:
        dev = default_jax_device()
    elif isinstance(device, Place):
        dev = device.jax_device()
    else:
        dev = Place(*_parse_device_str(device)).jax_device() if isinstance(
            device, str) else device
    try:
        return dev.memory_stats() or {}
    except Exception:
        return {}


def _parse_device_str(s: str):
    if ":" in s:
        kind, idx = s.split(":", 1)
        return kind, int(idx)
    return s, 0


def memory_allocated(device=None) -> int:
    """Bytes currently in use on the device (PJRT ``bytes_in_use``)."""
    return int(_memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak bytes in use (PJRT ``peak_bytes_in_use``)."""
    return int(_memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool (``bytes_reserved`` /
    ``pool_bytes`` when the backend reports it; falls back to in-use)."""
    st = _memory_stats(device)
    return int(st.get("bytes_reserved", st.get("pool_bytes",
                                               st.get("bytes_in_use", 0))))


def max_memory_reserved(device=None) -> int:
    st = _memory_stats(device)
    return int(st.get("peak_bytes_reserved", st.get(
        "largest_alloc_size", st.get("peak_bytes_in_use", 0))))


def empty_cache() -> None:
    """Parity no-op: PJRT owns its BFC pool; there is no user-facing cache
    flush on TPU (documented divergence)."""


class _DeviceStatsNS:
    """Namespace so both ``paddle.device.tpu.*`` and ``paddle.device.cuda.*``
    spellings resolve (model-zoo code calls the latter unconditionally)."""

    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    empty_cache = staticmethod(empty_cache)

    @staticmethod
    def device_count() -> int:
        return device_count()

    @staticmethod
    def synchronize(device=None) -> None:
        # XLA dispatch is async. TPU executes enqueued programs in order per
        # core, so enqueueing a trivial program on each local device and
        # blocking on its result drains the pipeline (effects_barrier alone
        # only waits for side-effecting computations).
        import jax
        import jax.numpy as jnp

        try:
            jax.effects_barrier()
        except Exception:
            pass  # older jax without effects_barrier: the per-device
            #       block_until_ready below still drains compute
        devs = ([default_jax_device()] if device is None
                else [device.jax_device() if isinstance(device, Place)
                      else default_jax_device()])
        for d in devs:
            jax.block_until_ready(
                jax.jit(lambda x: x + 1, device=d)(jnp.zeros(())))


tpu = _DeviceStatsNS()
cuda = _DeviceStatsNS()
xpu = _DeviceStatsNS()


def synchronize(device=None) -> None:
    _DeviceStatsNS.synchronize(device)


def force_platform(platform: str, device_count: Optional[int] = None) -> None:
    """Pin the jax platform programmatically, even in environments where a
    TPU plugin's sitecustomize overrides ``JAX_PLATFORMS`` env vars.

    If backends were already initialized, drops the stale clients and
    re-initializes — which invalidates any live jax arrays/executables, so
    call this FIRST in a process (examples/tests do, via
    ``force_platform_from_env``). ``device_count`` forces a virtual device
    count on the cpu platform (the SURVEY §4 fake-mesh pattern).
    """
    import os

    os.environ["JAX_PLATFORMS"] = platform
    if device_count is not None and platform == "cpu":
        flag = f"--xla_force_host_platform_device_count={device_count}"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    import warnings

    # our own device-list memos may hold pre-pin results (even a cached
    # backend FAILURE) — always drop them, backends latched or not
    _devices_of_type.cache_clear()
    _accelerator_type.cache_clear()
    try:
        from jax._src import xla_bridge
        if getattr(xla_bridge, "_backends", None):
            xla_bridge._clear_backends()
            xla_bridge.get_backend.cache_clear()
            # device lists are memoized separately (jax.local_devices etc.)
            # and would otherwise keep serving the pre-switch platform
            jax.clear_caches()
    except Exception as e:  # private jax API may move in an upgrade
        warnings.warn(f"force_platform: could not clear latched jax "
                      f"backends ({e!r}); the platform pin may not apply")
    try:
        jax.config.update("jax_platforms", platform)
    except Exception as e:
        warnings.warn(f"force_platform: jax_platforms update failed ({e!r})")
    if device_count is not None and platform == "cpu":
        try:
            jax.config.update("jax_num_cpu_devices", device_count)
        except Exception as e:
            warnings.warn(f"force_platform: jax_num_cpu_devices update "
                          f"failed ({e!r}); relying on XLA_FLAGS")


def force_platform_from_env() -> None:
    """Apply ``PADDLE_PLATFORM`` / ``PADDLE_PLATFORM_DEVICE_COUNT`` if set.

    Entry-point scripts call this before any jax work so test harnesses can
    pin them to the virtual CPU mesh (plain env vars are latched by TPU
    plugin sitecustomize hooks, so subprocess env alone is NOT enough)."""
    import os

    plat = os.environ.get("PADDLE_PLATFORM")
    if not plat:
        return
    cnt = os.environ.get("PADDLE_PLATFORM_DEVICE_COUNT")
    force_platform(plat, int(cnt) if cnt else None)
