"""Device / Place taxonomy.

Parity surface: ``phi::Place`` (upstream: paddle/phi/common/place.h) and
``paddle.device.set_device`` (python/paddle/device/__init__.py). TPU-native
design: a Place names a jax device; ``set_device`` selects the default
placement used by tensor factories; cross-place copies are ``jax.device_put``.
No DeviceContext/stream pool is needed — XLA/PJRT owns streams and events.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CUDAPlace", "CustomPlace",
    "set_device", "get_device", "get_all_devices", "device_count",
    "is_compiled_with_cuda", "is_compiled_with_tpu", "current_place",
]


class Place:
    """Identity of a physical device: (device_type, device_id)."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- mapping to jax ------------------------------------------------------
    def jax_device(self) -> jax.Device:
        devs = _devices_of_type(self.device_type)
        if not devs:
            raise RuntimeError(f"no {self.device_type!r} devices visible to jax")
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


def CPUPlace(device_id: int = 0) -> Place:
    return Place("cpu", device_id)


def TPUPlace(device_id: int = 0) -> Place:
    return Place("tpu", device_id)


def CUDAPlace(device_id: int = 0) -> Place:
    # Parity alias: there is no CUDA on TPU systems; accepted so reference
    # scripts run, mapped to the accelerator if present else CPU.
    return Place("tpu", device_id) if _accelerator_type() == "tpu" else Place("cpu", device_id)


def CustomPlace(device_type: str, device_id: int = 0) -> Place:
    return Place(device_type, device_id)


@functools.lru_cache(maxsize=None)
def _devices_of_type(device_type: str):
    try:
        all_devs = jax.devices()
    except RuntimeError:
        all_devs = []
    if device_type == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple(d for d in all_devs if d.platform == "cpu")
    # A TPU may surface as platform 'tpu' or (via tunnel) an experimental
    # platform; treat any non-cpu accelerator as the 'tpu' place.
    accel = tuple(d for d in all_devs if d.platform != "cpu")
    if device_type in ("tpu", "gpu", "xpu"):
        return accel
    return tuple(d for d in all_devs if d.platform == device_type)


@functools.lru_cache(maxsize=1)
def _accelerator_type() -> str:
    try:
        if any(d.platform != "cpu" for d in jax.devices()):
            return "tpu"
    except RuntimeError:
        pass
    return "cpu"


_current_place: Optional[Place] = None


def set_device(device: Union[str, Place]) -> Place:
    """``paddle.device.set_device('tpu')`` / ``('tpu:0')`` / ``('cpu')``."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    dev = device.lower()
    if dev in ("gpu", "cuda", "xpu"):
        dev = "tpu" if _accelerator_type() == "tpu" else "cpu"
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        _current_place = Place(kind, int(idx))
    else:
        _current_place = Place(dev, 0)
    _current_place.jax_device()  # validate eagerly
    return _current_place


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = Place(_accelerator_type(), 0)
    return _current_place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count(device_type: Optional[str] = None) -> int:
    return len(_devices_of_type(device_type or current_place().device_type))


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return _accelerator_type() == "tpu"


def default_jax_device() -> jax.Device:
    return current_place().jax_device()
