"""``paddle.geometric``: graph message passing + segment ops.

Parity surface: python/paddle/geometric/ (send_u_recv, send_ue_recv,
send_uv, segment_sum/mean/max/min, reindex_graph, sample_neighbors; upstream
kernels paddle/phi/kernels/gpu/graph_send_recv_*).

TPU-native design: message passing is segment-reduction — jax's
``segment_sum``-family ops lower to XLA scatters with static output size
(``out_size``/num_segments must be static, matching the reference's
out_size argument).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..ops._helpers import ensure_tensor, register_op

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min", "reindex_graph",
           "sample_neighbors"]

# frozenset: _segment_reduce is jax-traced (reachable from apply()), so a
# mutable module global read there would be baked in at trace time
_REDUCES = frozenset({"sum", "mean", "max", "min"})


def _segment_reduce(data, seg_ids, num, pool):
    if pool == "sum":
        return jax.ops.segment_sum(data, seg_ids, num)
    if pool == "mean":
        s = jax.ops.segment_sum(data, seg_ids, num)
        cnt = jax.ops.segment_sum(jnp.ones_like(seg_ids, data.dtype),
                                  seg_ids, num)
        return s / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (data.ndim - 1))
    if pool in ("max", "min"):
        out = (jax.ops.segment_max if pool == "max"
               else jax.ops.segment_min)(data, seg_ids, num)
        # empty segments -> 0 (reference semantics), detected via counts so
        # integer dtypes keep their dtype and legitimate +/-inf survive
        cnt = jax.ops.segment_sum(jnp.ones_like(seg_ids, jnp.int32),
                                  seg_ids, num)
        mask = (cnt > 0).reshape((-1,) + (1,) * (data.ndim - 1))
        return jnp.where(mask, out, jnp.zeros((), out.dtype))
    raise ValueError(f"reduce_op must be one of {_REDUCES}, got {pool!r}")


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """Gather x[src] → scatter-reduce at dst (reference: graph_send_recv)."""
    x = ensure_tensor(x)
    src = ensure_tensor(src_index)
    dst = ensure_tensor(dst_index)
    num = int(out_size) if out_size is not None else int(x.shape[0])

    def f(xd, s, d):
        return _segment_reduce(xd[s], d, num, reduce_op)

    return apply("send_u_recv", f, x, src, dst)


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, then reduce at dst."""
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    src = ensure_tensor(src_index)
    dst = ensure_tensor(dst_index)
    num = int(out_size) if out_size is not None else int(x.shape[0])

    def f(xd, yd, s, d):
        m = xd[s]
        if message_op == "add":
            m = m + yd
        elif message_op == "sub":
            m = m - yd
        elif message_op == "mul":
            m = m * yd
        elif message_op == "div":
            m = m / yd
        else:
            raise ValueError(f"message_op {message_op!r}")
        return _segment_reduce(m, d, num, reduce_op)

    return apply("send_ue_recv", f, x, y, src, dst)


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge message from both endpoints (reference: graph_send_uv)."""
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    src = ensure_tensor(src_index)
    dst = ensure_tensor(dst_index)

    def f(xd, yd, s, d):
        a, b = xd[s], yd[d]
        if message_op == "add":
            return a + b
        if message_op == "sub":
            return a - b
        if message_op == "mul":
            return a * b
        if message_op == "div":
            return a / b
        raise ValueError(f"message_op {message_op!r}")

    return apply("send_uv", f, x, y, src, dst)


def _make_segment(pool):
    def seg(data, segment_ids, name=None):
        data = ensure_tensor(data)
        seg_ids = ensure_tensor(segment_ids)
        # static segment count: max id + 1 read host-side (reference
        # semantics: ids must be sorted/valid; XLA needs the bound static)
        num = int(jnp.max(seg_ids._data)) + 1 if seg_ids._data.size else 0

        def f(d, s):
            return _segment_reduce(d, s, num, pool)

        return apply(f"segment_{pool}", f, data, seg_ids)

    seg.__name__ = f"segment_{pool}"
    return seg


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")

for _name, _fn in (("segment_sum", segment_sum), ("segment_mean", segment_mean),
                   ("segment_max", segment_max), ("segment_min", segment_min)):
    register_op(_name, _fn)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference: phi reindex kernel).
    Host-side (graph sampling is a data-pipeline step, not a device op)."""
    import numpy as np
    xs = np.asarray(ensure_tensor(x)._data)
    nb = np.asarray(ensure_tensor(neighbors)._data)
    # paddle orders: x's ids first keep their order, then new neighbor ids
    order = {int(v): i for i, v in enumerate(xs)}
    nxt = len(order)
    for v in nb:
        if int(v) not in order:
            order[int(v)] = nxt
            nxt += 1
    reindex_src = np.asarray([order[int(v)] for v in nb], np.int64)
    counts = np.asarray(ensure_tensor(count)._data)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), counts)
    out_nodes = np.array(sorted(order, key=order.__getitem__), dtype=np.int64)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))


def sample_neighbors(row, colptr, input_nodes, sample_size: int = -1,
                     eids=None, return_eids: bool = False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling over CSC (reference: graph_sample_neighbors).
    Host-side numpy (data pipeline); deterministic via the global seed."""
    import numpy as np

    from ..core.random import default_generator
    rowd = np.asarray(ensure_tensor(row)._data)
    ptr = np.asarray(ensure_tensor(colptr)._data)
    nodes = np.asarray(ensure_tensor(input_nodes)._data)
    rng = np.random.default_rng(int(jax.random.randint(
        default_generator.split_key(), (), 0, 2 ** 31 - 1)))
    eids_np = None if eids is None else np.asarray(ensure_tensor(eids)._data)
    out_nb, out_cnt, out_eid = [], [], []
    for n in nodes:
        lo, hi = int(ptr[n]), int(ptr[n + 1])
        pos = np.arange(lo, hi)
        if 0 < sample_size < len(pos):
            pos = rng.choice(pos, size=sample_size, replace=False)
        out_nb.append(rowd[pos])
        out_cnt.append(len(pos))
        if return_eids:
            out_eid.append(eids_np[pos] if eids_np is not None
                           else pos.astype(np.int64))
    nb = np.concatenate(out_nb) if out_nb else np.zeros((0,), rowd.dtype)
    cnt = Tensor(jnp.asarray(np.asarray(out_cnt, np.int32)))
    if return_eids:
        ei = np.concatenate(out_eid) if out_eid else np.zeros((0,), np.int64)
        return Tensor(jnp.asarray(nb)), cnt, Tensor(jnp.asarray(ei))
    return Tensor(jnp.asarray(nb)), cnt
