"""``nn.Layer``: the module system.

Parity surface: python/paddle/nn/layer/layers.py (upstream ``Layer`` — module
tree, parameters/buffers, hooks, state_dict, train/eval, apply, to). The
payload tensors are jax arrays, so ``state_dict`` interops with orbax and
``to_static`` functionalization picks parameters up through the state
registry.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype
from ..core.tensor import Parameter, RemovableHandle, Tensor, register_state_tensor, to_tensor
from .initializer import Constant, XavierUniform, _to_initializer

__all__ = ["Layer", "ParamAttr", "LazyGuard"]

# --- lazy init (parity: paddle.LazyGuard / python/paddle/nn/initializer/
# lazy_init.py): parameters created inside the guard defer their initializer
# (no device allocation at model construction); any Layer.__call__
# materializes all pending params first.
_lazy_mode = False
_lazy_params: list = []


def _lazy_guard_active() -> bool:
    return _lazy_mode


def _materialize_lazy_params() -> None:
    pending, _lazy_params[:] = list(_lazy_params), []
    for ref, init, shape, dtype in pending:
        p = ref()  # weakref: a discarded lazy model must not be allocated
        if p is not None and p._data is None:
            p._set_data(init(shape, dtype))


class LazyGuard:
    """``with LazyGuard(): model = Net()`` — construct without allocating."""

    def __enter__(self):
        global _lazy_mode
        self._prev = _lazy_mode
        _lazy_mode = True
        return self

    def __exit__(self, *exc):
        global _lazy_mode
        _lazy_mode = self._prev
        return False


class ParamAttr:
    """Parity: paddle.ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or attr is True:  # True = "default attr" (paddle)
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # an initializer instance
        return ParamAttr(initializer=attr)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype: Any = "float32"):
        self.training = True
        self._dtype = _dtype.convert_dtype(dtype)
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names: set = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._name_scope = name_scope or type(self).__name__.lower()

    # --- attribute capture --------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (subs, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            subs[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
        else:
            if params is not None and name in params and value is None:
                del params[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name: str) -> None:
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # --- construction helpers ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = _dtype.convert_dtype(dtype) or self._dtype
        from .initializer import _global_default
        # precedence (reference semantics): explicit ParamAttr initializer >
        # set_global_initializer > the layer's own default
        init = attr.initializer or _global_default(is_bias) \
            or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        init = _to_initializer(init)
        if _lazy_guard_active():
            # LazyGuard: defer running the initializer (no device allocation
            # at construction); materialized at first Layer.__call__
            p = Parameter(None, name=attr.name, trainable=attr.trainable)
            import weakref
            _lazy_params.append(
                (weakref.ref(p), init, tuple(int(s) for s in shape), dtype))
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
            return p
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]) -> Optional[Parameter]:
        if parameter is None:
            self._parameters.pop(name, None)
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True) -> None:
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = to_tensor(tensor)
        self._buffers[name] = tensor
        if tensor is not None:
            tensor.persistable = persistable
            register_state_tensor(tensor)
        if not persistable:
            self._non_persistable_buffer_names.add(name)

    # --- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (name + ("." if name else "") + pname, p)

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (name + ("." if name else "") + bname, b)

    def _traverse(self, prefix: str, include_sublayers: bool):
        yield prefix, self
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = prefix + ("." if prefix else "") + sname
                yield from sub._traverse(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        yield from self._sub_layers.items()

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        out = []
        for name, l in self._traverse("", True):
            if name == "" and not include_self:
                continue
            out.append(l)
        return out

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        for name, l in self._traverse(prefix, True):
            if name == prefix and not include_self:
                continue
            yield name, l

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def full_name(self) -> str:
        return self._name_scope

    # --- train/eval ---------------------------------------------------------
    def train(self) -> "Layer":
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self) -> "Layer":
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # --- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook: Callable) -> RemovableHandle:
        h = RemovableHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[h.hook_id] = hook
        return h

    def register_forward_post_hook(self, hook: Callable) -> RemovableHandle:
        h = RemovableHandle(self._forward_post_hooks)
        self._forward_post_hooks[h.hook_id] = hook
        return h

    # --- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        if _lazy_params:
            _materialize_lazy_params()
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # --- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self._traverse(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and bname not in layer._non_persistable_buffer_names:
                    dest[name + ("." if name else "") + bname] = b
        return dest

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(arr.shape) != tuple(tgt._data.shape):
                raise ValueError(
                    f"shape mismatch for {k}: checkpoint {tuple(arr.shape)} vs "
                    f"model {tuple(tgt._data.shape)}")
            tgt._set_data(arr.astype(tgt._data.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # --- dtype/device cast --------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None) -> "Layer":
        from ..core.tensor import _parse_place
        dtype = _dtype.convert_dtype(dtype)
        for t in list(self.parameters()) + list(self.buffers()):
            arr = t._data
            if dtype is not None and jnp.issubdtype(arr.dtype, jnp.floating):
                arr = arr.astype(dtype)
            if device is not None:
                from .. import device as _device
                arr = _device.device_put(arr, _parse_place(device))
            t._set_data(arr)
        if dtype is not None:
            self._dtype = dtype
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self) -> None:
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            mod_str = repr(sub)
            mod_str = "\n".join(
                ["  " + l for l in mod_str.split("\n")])
            lines.append(f"  ({name}): " + mod_str.lstrip())
        main = type(self).__name__ + "(" + extra
        if lines:
            main += "\n" + "\n".join(lines) + "\n"
        return main + ")"

    def extra_repr(self) -> str:
        return ""
