"""Weight initializers.

Parity surface: python/paddle/nn/initializer/ (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign, Dirac, Orthogonal). Each initializer is a callable
``(shape, dtype) -> jax array`` drawing from the global splittable PRNG.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random import default_generator

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight (out_c, in_c/groups, *k)
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError

    # parity with paddle: initializer(param) re-initializes an existing param
    def init_(self, param):
        param._set_data(self(tuple(param._data.shape), param._data.dtype))
        return param


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = default_generator.split_key()
        return jax.random.normal(k, shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = default_generator.split_key()
        return jax.random.truncated_normal(k, self.a, self.b, shape, dtype) \
            * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = default_generator.split_key()
        return jax.random.uniform(k, shape, dtype, minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = default_generator.split_key()
        return jax.random.normal(k, shape, dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = default_generator.split_key()
        return jax.random.uniform(k, shape, dtype, minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope) \
            if self.nonlinearity == "leaky_relu" else calculate_gain(self.nonlinearity)
        std = gain / math.sqrt(fi)
        k = default_generator.split_key()
        return jax.random.normal(k, shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope) \
            if self.nonlinearity == "leaky_relu" else calculate_gain(self.nonlinearity)
        limit = gain * math.sqrt(3.0 / fi)
        k = default_generator.split_key()
        return jax.random.uniform(k, shape, dtype, minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import Tensor
        v = self.value._data if isinstance(self.value, Tensor) else np.asarray(self.value)
        arr = jnp.asarray(v, dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = default_generator.split_key()
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(k, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic * self.groups)):
            idx = (i, i % ic) + tuple(centers)
            out[idx] = 1.0
        return jnp.asarray(out, dtype)


def _to_initializer(init):
    if isinstance(init, Initializer):
        return init
    if callable(init):
        return init
    raise TypeError(f"cannot use {init!r} as an initializer")


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None) -> None:
    """Default initializers for subsequently-created parameters (reference:
    paddle.nn.initializer.set_global_initializer). Pass None to reset."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_default(is_bias: bool):
    return _global_bias_init if is_bias else _global_weight_init
