"""``paddle.nn`` namespace. Parity: python/paddle/nn/__init__.py."""

from .layer import Layer, ParamAttr  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Identity, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, Pad1D, Pad2D,
    Pad3D, CosineSimilarity, PixelShuffle, PixelUnshuffle,
    ChannelShuffle, Unfold, Fold,
    Unflatten, FeatureAlphaDropout, PairwiseDistance, Bilinear, RReLU,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    FractionalMaxPool2D, FractionalMaxPool3D,
    ZeroPad1D, ZeroPad2D, ZeroPad3D, EmbeddingBag,
)
from .conv import (Conv1D, Conv2D, Conv3D, Conv2DTranspose,  # noqa: F401
                   Conv1DTranspose, Conv3DTranspose)
from .norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, RMSNorm,
    LocalResponseNorm, SpectralNorm,
)
from .pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    LPPool1D, LPPool2D,
)
from .activation import (  # noqa: F401
    ReLU, ReLU6, GELU, Sigmoid, Tanh, Softmax, LogSoftmax, LeakyReLU, ELU, SELU,
    CELU, SiLU, Swish, Mish, Hardswish, Hardsigmoid, Hardtanh, Hardshrink,
    Softshrink, Softplus, Softsign, Silu, Softmax2D, Tanhshrink,
    ThresholdedReLU, LogSigmoid,
    Maxout, PReLU, GLU,
)
from .container import Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from .loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, CosineEmbeddingLoss,
    TripletMarginLoss, HingeEmbeddingLoss,
    SoftMarginLoss, MultiMarginLoss, PoissonNLLLoss, GaussianNLLLoss,
    CTCLoss, RNNTLoss, AdaptiveLogSoftmaxWithLoss,
    MultiLabelSoftMarginLoss, TripletMarginWithDistanceLoss,
    HSigmoidLoss,
)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from ..optimizer import (  # noqa: F401  (parity: paddle.nn.ClipGradBy*)
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
)
from . import quant  # noqa: F401
from . import decode  # noqa: F401
from .initializer import set_global_initializer  # noqa: F401
