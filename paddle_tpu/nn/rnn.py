"""Recurrent layers: SimpleRNN / LSTM / GRU cells and multi-layer wrappers.

Parity surface: python/paddle/nn/layer/rnn.py (upstream ``RNNCellBase``,
``SimpleRNNCell``, ``LSTMCell``, ``GRUCell``, ``RNN``, ``BiRNN``,
``SimpleRNN``, ``LSTM``, ``GRU`` — no line cites: reference mount was empty,
see SURVEY.md provenance). TPU-native design: one full-sequence recurrence is
ONE dispatched op whose body is a ``jax.lax.scan`` — static-shape,
compiler-friendly control flow (no Python loop per timestep), with the vjp
taken through the whole scan at dispatch time. Gate orders match the
reference: LSTM chunks [i, f, g, o]; GRU chunks [r, z, c] with
``h' = z*h + (1-z)*c``.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply
from . import functional as F
from .initializer import Uniform
from .layer import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
    "SimpleRNN", "LSTM", "GRU",
]


# ---------------------------------------------------------------------------
# pure jax cell step functions (shared by cells and scans)
# ---------------------------------------------------------------------------
def _simple_step(xt, h, w_ih, w_hh, b_ih, b_hh, activation):
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    return act(xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh)


def _lstm_step(xt, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    return o * jnp.tanh(c_new), c_new  # h', c'


def _gru_step(xt, h, w_ih, w_hh, b_ih, b_hh):
    xg = xt @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    xr, xz, xc = jnp.split(xg, 3, axis=-1)
    hr, hz, hc = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    return z * h + (1.0 - z) * c


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------
class RNNCellBase(Layer):
    """Base: holds (gates*hidden, input) / (gates*hidden, hidden) weights with
    the reference's Uniform(-1/sqrt(hidden), 1/sqrt(hidden)) init."""

    def _make_params(self, input_size: int, hidden_size: int, n_gates: int):
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (n_gates * hidden_size, input_size), default_initializer=init)
        self.weight_hh = self.create_parameter(
            (n_gates * hidden_size, hidden_size), default_initializer=init)
        self.bias_ih = self.create_parameter(
            (n_gates * hidden_size,), is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter(
            (n_gates * hidden_size,), is_bias=True, default_initializer=init)

    def _zero_state(self, x: Tensor, hidden_size: int):
        batch = x.shape[0]
        return Tensor(jnp.zeros((batch, hidden_size), x._data.dtype))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size: int, hidden_size: int,
                 activation: str = "tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError(f"unknown activation {activation!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self._make_params(input_size, hidden_size, 1)

    def forward(self, inputs: Tensor, states: Optional[Tensor] = None):
        h = states if states is not None else self._zero_state(
            inputs, self.hidden_size)
        out = apply("simple_rnn_cell", _simple_step, inputs, h,
                    self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
                    activation=self.activation)
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size: int, hidden_size: int, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._make_params(input_size, hidden_size, 4)

    def forward(self, inputs: Tensor, states=None):
        if states is None:
            h = self._zero_state(inputs, self.hidden_size)
            c = self._zero_state(inputs, self.hidden_size)
        else:
            h, c = states
        h_new, c_new = apply("lstm_cell", _lstm_step, inputs, h, c,
                             self.weight_ih, self.weight_hh, self.bias_ih,
                             self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size: int, hidden_size: int, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._make_params(input_size, hidden_size, 3)

    def forward(self, inputs: Tensor, states: Optional[Tensor] = None):
        h = states if states is not None else self._zero_state(
            inputs, self.hidden_size)
        out = apply("gru_cell", _gru_step, inputs, h, self.weight_ih,
                    self.weight_hh, self.bias_ih, self.bias_hh)
        return out, out


# ---------------------------------------------------------------------------
# full-sequence scans (each is ONE dispatched op over lax.scan)
# ---------------------------------------------------------------------------
def _scan_layer(mode, x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_lens, *,
                reverse: bool, activation: str):
    """x: [B, T, I] batch-major. Returns (ys [B, T, H], h_T, c_T).

    ``seq_lens`` (or None) masks padded steps: state freezes past the valid
    length; reverse scans start at the last valid step (the reference's
    sequence_length semantics).
    """
    xs = jnp.swapaxes(x, 0, 1)  # [T, B, I]
    T = xs.shape[0]
    ts = jnp.arange(T) if seq_lens is not None else None

    def step(carry, inp):
        t, xt = inp
        h, c = carry
        if mode == "LSTM":
            h_new, c_new = _lstm_step(xt, h, c, w_ih, w_hh, b_ih, b_hh)
        elif mode == "GRU":
            h_new, c_new = _gru_step(xt, h, w_ih, w_hh, b_ih, b_hh), c
        else:
            h_new, c_new = _simple_step(xt, h, w_ih, w_hh, b_ih, b_hh,
                                        activation), c
        if seq_lens is not None:
            valid = (t < seq_lens)[:, None]
            h_new = jnp.where(valid, h_new, h)
            c_new = jnp.where(valid, c_new, c)
        return (h_new, c_new), h_new

    if reverse and seq_lens is None:
        xs = xs[::-1]
    if reverse and seq_lens is not None:
        # flip only the valid prefix of each row so the reverse scan starts
        # at the last real token: index T-1-t clamped into the valid range
        idx = jnp.clip(seq_lens[None, :] - 1 - jnp.arange(T)[:, None], 0, T - 1)
        xs = jnp.take_along_axis(xs, idx[:, :, None], axis=0)

    inp = (ts, xs) if seq_lens is not None else (jnp.zeros((T,)), xs)
    (h_T, c_T), ys = lax.scan(step, (h0, c0), inp)

    if reverse and seq_lens is None:
        ys = ys[::-1]
    if reverse and seq_lens is not None:
        idx = jnp.clip(seq_lens[None, :] - 1 - jnp.arange(T)[:, None], 0, T - 1)
        ys = jnp.take_along_axis(ys, idx[:, :, None], axis=0)
    if seq_lens is not None:
        ys = jnp.where((jnp.arange(T)[:, None] < seq_lens)[:, :, None], ys, 0.0)
    return jnp.swapaxes(ys, 0, 1), h_T, c_T


class RNN(Layer):
    """Run a cell over a sequence (parity: paddle.nn.RNN). The recurrence is
    dispatched as one lax.scan op, not a Python timestep loop."""

    def __init__(self, cell: RNNCellBase, is_reverse: bool = False,
                 time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs: Tensor, initial_states=None,
                sequence_length: Optional[Tensor] = None):
        # exact-type cells take the fused lax.scan fast path; subclassed /
        # custom cells may override forward, so they run through it step by
        # step (correct but unfused)
        if type(self.cell) is LSTMCell:
            mode = "LSTM"
        elif type(self.cell) is GRUCell:
            mode = "GRU"
        elif type(self.cell) is SimpleRNNCell:
            mode = "RNN_TANH"
        else:
            return self._generic_forward(inputs, initial_states,
                                         sequence_length)
        x = inputs.transpose([1, 0, 2]) if self.time_major else inputs
        hsz = self.cell.hidden_size
        batch = x.shape[0]
        if initial_states is None:
            z = Tensor(jnp.zeros((batch, hsz), x._data.dtype))
            h0, c0 = z, z
        elif isinstance(initial_states, (tuple, list)):
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, initial_states
        ys, h_T, c_T = _run_scan(mode, x, h0, c0, self.cell.weight_ih,
                                 self.cell.weight_hh, self.cell.bias_ih,
                                 self.cell.bias_hh, sequence_length,
                                 reverse=self.is_reverse,
                                 activation=getattr(self.cell, "activation",
                                                    "tanh"))
        if self.time_major:
            ys = ys.transpose([1, 0, 2])
        final = (h_T, c_T) if mode == "LSTM" else h_T
        return ys, final

    def _generic_forward(self, inputs: Tensor, initial_states,
                         sequence_length):
        if sequence_length is not None:
            raise NotImplementedError(
                "sequence_length is only supported with the built-in cells")
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        T = x.shape[0]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        state = initial_states
        outs: list = [None] * T
        for t in steps:
            y, state = self.cell(x[t], state)
            outs[t] = y
        ys = _stack0(outs)
        if not self.time_major:
            ys = ys.transpose([1, 0, 2])
        return ys, state


def _run_scan(mode, x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_lens, *, reverse,
              activation):
    args = [x, h0, c0, w_ih, w_hh, b_ih, b_hh]
    if seq_lens is not None:
        sl = seq_lens if isinstance(seq_lens, Tensor) else Tensor(
            jnp.asarray(seq_lens))
        return apply(f"rnn_scan_{mode.lower()}",
                     lambda x_, h_, c_, wi, wh, bi, bh, s: _scan_layer(
                         mode, x_, h_, c_, wi, wh, bi, bh, s,
                         reverse=reverse, activation=activation),
                     *args, sl)
    return apply(f"rnn_scan_{mode.lower()}",
                 lambda x_, h_, c_, wi, wh, bi, bh: _scan_layer(
                     mode, x_, h_, c_, wi, wh, bi, bh, None,
                     reverse=reverse, activation=activation),
                 *args)


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (parity: paddle.nn.BiRNN)."""

    def __init__(self, cell_fw: RNNCellBase, cell_bw: RNNCellBase,
                 time_major: bool = False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        y_bw, s_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        return _concat_last(y_fw, y_bw), (s_fw, s_bw)


def _concat_last(a: Tensor, b: Tensor) -> Tensor:
    return apply("concat", lambda x, y: jnp.concatenate([x, y], axis=-1), a, b)


# ---------------------------------------------------------------------------
# multi-layer recurrent networks
# ---------------------------------------------------------------------------
class _RNNBase(Layer):
    MODE = "RNN_TANH"
    N_GATES = 1

    def __init__(self, input_size: int, hidden_size: int, num_layers: int = 1,
                 direction: str = "forward", time_major: bool = False,
                 dropout: float = 0.0, activation: str = "tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirectional = direction != "forward"
        self.num_directions = 2 if self.bidirectional else 1
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        gh = self.N_GATES * hidden_size
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * self.num_directions
            for d in range(self.num_directions):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                setattr(self, f"weight_ih{sfx}", self.create_parameter(
                    (gh, in_sz), default_initializer=init))
                setattr(self, f"weight_hh{sfx}", self.create_parameter(
                    (gh, hidden_size), default_initializer=init))
                setattr(self, f"bias_ih{sfx}", self.create_parameter(
                    (gh,), is_bias=True, default_initializer=init))
                setattr(self, f"bias_hh{sfx}", self.create_parameter(
                    (gh,), is_bias=True, default_initializer=init))

    def _layer_params(self, layer: int, d: int):
        sfx = f"_l{layer}" + ("_reverse" if d else "")
        return (getattr(self, f"weight_ih{sfx}"),
                getattr(self, f"weight_hh{sfx}"),
                getattr(self, f"bias_ih{sfx}"),
                getattr(self, f"bias_hh{sfx}"))

    def forward(self, inputs: Tensor, initial_states=None,
                sequence_length=None):
        x = inputs.transpose([1, 0, 2]) if self.time_major else inputs
        batch = x.shape[0]
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        is_lstm = self.MODE == "LSTM"

        if initial_states is None:
            zeros = Tensor(jnp.zeros((L * D, batch, H), x._data.dtype))
            init_h, init_c = zeros, zeros
        elif is_lstm:
            init_h, init_c = initial_states
        else:
            init_h, init_c = initial_states, initial_states

        h_finals, c_finals = [], []
        out = x
        for layer in range(L):
            dir_outs = []
            for d in range(D):
                idx = layer * D + d
                h0 = init_h[idx]
                c0 = init_c[idx]
                w_ih, w_hh, b_ih, b_hh = self._layer_params(layer, d)
                ys, h_T, c_T = _run_scan(
                    self.MODE, out, h0, c0, w_ih, w_hh, b_ih, b_hh,
                    sequence_length, reverse=bool(d),
                    activation=self.activation)
                dir_outs.append(ys)
                h_finals.append(h_T)
                c_finals.append(c_T)
            out = dir_outs[0] if D == 1 else _concat_last(*dir_outs)
            if self.dropout and layer < L - 1 and self.training:
                out = F.dropout(out, p=self.dropout, training=True)

        h_n = _stack0(h_finals)
        if self.time_major:
            out = out.transpose([1, 0, 2])
        if is_lstm:
            return out, (h_n, _stack0(c_finals))
        return out, h_n


def _stack0(ts) -> Tensor:
    return apply("stack", lambda *xs: jnp.stack(xs, axis=0), *ts)


class SimpleRNN(_RNNBase):
    """Parity: paddle.nn.SimpleRNN (upstream puts ``activation`` FOURTH,
    before direction — unlike LSTM/GRU which have no activation arg)."""
    MODE = "RNN_TANH"
    N_GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 activation="tanh", direction="forward", time_major=False,
                 dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation)


class LSTM(_RNNBase):
    """Parity: paddle.nn.LSTM (gate order [i, f, g, o])."""
    MODE = "LSTM"
    N_GATES = 4

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class GRU(_RNNBase):
    """Parity: paddle.nn.GRU (gate order [r, z, c], h' = z*h + (1-z)*c)."""
    MODE = "GRU"
    N_GATES = 3

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout)
