"""Normalization layers. Parity: python/paddle/nn/layer/norm.py."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, register_state_tensor, to_tensor
from . import functional as F
from .initializer import Constant
from .layer import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
    "RMSNorm", "LocalResponseNorm", "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", to_tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", to_tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self.momentum,
                            epsilon=self.epsilon, data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCL" else data_format,
                         use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else data_format,
                         use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    TPU-native note: under ``to_static`` + data-parallel sharding, XLA computes
    batch statistics over the *global* batch automatically when the batch axis
    is sharded — so SyncBatchNorm degenerates to BatchNorm in the compiled
    path. In eager mode it syncs stats with an all-reduce over the dp group if
    a parallel env is initialized (upstream:
    python/paddle/nn/layer/norm.py SyncBatchNorm + sync_batch_norm op).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for l in layer.sublayers(include_self=True):
            for name, sub in list(l._sub_layers.items()):
                if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                    new = SyncBatchNorm(sub.num_features, sub.momentum, sub.epsilon,
                                        data_format=sub.data_format)
                    new.weight, new.bias = sub.weight, sub.bias
                    new._mean, new._variance = sub._mean, sub._variance
                    l._sub_layers[name] = new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """RMS norm (paddle.incubate.nn.FusedRMSNorm capability; Llama building
    block)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k,
                                     self.data_format)


class SpectralNorm(Layer):
    """Spectral norm via power iteration (upstream: paddle/nn/layer/norm.py)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..ops.creation import randn
        u = randn((h,))
        v = randn((w,))
        self.register_buffer("weight_u", u)
        self.register_buffer("weight_v", v)

    def forward(self, weight):
        from ..core.tensor import apply
        from ..core.tracing import no_grad
        dim, eps, iters = self.dim, self.eps, self.power_iters

        # power iteration advances the persistent u/v buffers (no grad), so
        # sigma converges across steps like the reference implementation
        with no_grad():
            wm_c = jnp.moveaxis(weight._data, dim, 0).reshape(
                weight._data.shape[dim], -1)
            u, v = self.weight_u._data, self.weight_v._data
            for _ in range(iters):
                v = wm_c.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm_c @ v
                u = u / (jnp.linalg.norm(u) + eps)
            self.weight_u._set_data(u)
            self.weight_v._set_data(v)

        def f(w, uu, vv):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            sigma = uu @ wm @ vv
            return w / sigma

        return apply("spectral_norm", f, weight, self.weight_u, self.weight_v)
