"""``paddle.nn.quant`` — weight-only quantization helpers.

Parity: python/paddle/nn/quant/ (weight_quantize / weight_dequantize /
weight_only_linear, llm.int8 path). TPU-native notes: int8 weights live as
int8 arrays + per-channel fp scales; matmuls upcast to bf16 at use (XLA
fuses the dequant into the matmul epilogue — there is no separate int8 MXU
path to schedule by hand).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..ops._helpers import ensure_tensor, register_op

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]


def weight_quantize(x, algo: str = "weight_only_int8", arch=None, name=None):
    """Per-output-channel symmetric int8/int4 quantization. Returns
    (quantized int8 weight, fp32 scales)."""
    x = ensure_tensor(x)
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported quant algo {algo!r}")
    qmax = 7.0 if algo == "weight_only_int4" else 127.0

    def f(w):
        scale = jnp.max(jnp.abs(w), axis=0) / qmax  # per out-channel (k, n)
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(w / scale[None, :]), -qmax, qmax)
        return q.astype(jnp.int8), scale.astype(jnp.float32)

    out, scale = apply("weight_quantize", f, x, differentiable=False)
    return out, scale


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float32", name=None):
    x, scale = ensure_tensor(x), ensure_tensor(scale)
    from ..core.dtype import convert_dtype
    dt = convert_dtype(out_dtype)
    return apply("weight_dequantize",
                 lambda q, s: q.astype(dt) * s.astype(dt)[None, :],
                 x, scale, differentiable=False)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1,
                       name=None):
    """x @ dequant(weight) + bias with the dequant fused into the matmul."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    extras = []
    if weight_scale is not None:
        extras.append(ensure_tensor(weight_scale))
    if bias is not None:
        extras.append(ensure_tensor(bias))

    def f(a, w, *rest):
        i = 0
        if weight_scale is not None:
            s = rest[i]
            i += 1
            w = w.astype(a.dtype) * s.astype(a.dtype)[None, :]
        else:
            w = w.astype(a.dtype)
        out = a @ w
        if bias is not None:
            out = out + rest[i]
        return out

    return apply("weight_only_linear", f, x, weight, *extras)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0, name=None):
    """LLM.int8 (Dettmers et al.): activations quantize dynamically
    per-row to int8 and the matmul EXECUTES in int8 with int32
    accumulation (``lax.dot_general(..., preferred_element_type=int32)``
    — the TPU MXU's int8 path); in-feature columns whose activation
    magnitude exceeds ``threshold`` stay in floating point and ride a
    second matmul (static-shape masking instead of gather, so the program
    compiles once)."""
    import jax.lax as lax

    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if jnp.issubdtype(weight._data.dtype, jnp.floating):
        # unquantized weights: keep the historical exact-fp behavior
        # rather than silently truncating fractional values to int8
        return weight_only_linear(x, weight, bias=bias,
                                  weight_scale=weight_scale)
    extras = []
    if weight_scale is not None:
        extras.append(ensure_tensor(weight_scale))
    if bias is not None:
        extras.append(ensure_tensor(bias))

    def f(a, w, *rest):
        i = 0
        if weight_scale is not None:
            w_scale = rest[i].astype(jnp.float32)
            i += 1
        else:
            w_scale = jnp.ones((w.shape[1],), jnp.float32)
        lead = a.shape[:-1]
        a2 = a.reshape((-1, a.shape[-1])).astype(jnp.float32)
        # outlier in-features: any row exceeding threshold
        col_amax = jnp.max(jnp.abs(a2), axis=0)
        outlier = col_amax > jnp.float32(threshold)
        a_int_src = jnp.where(outlier[None, :], 0.0, a2)
        a_fp = jnp.where(outlier[None, :], a2, 0.0)
        # per-row symmetric int8 quantization of the non-outlier part
        row_scale = jnp.maximum(jnp.max(jnp.abs(a_int_src), axis=1,
                                        keepdims=True), 1e-8) / 127.0
        a8 = jnp.clip(jnp.round(a_int_src / row_scale), -127, 127
                      ).astype(jnp.int8)
        w8 = w.astype(jnp.int8)
        y32 = lax.dot_general(a8, w8, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
        y_int = y32.astype(jnp.float32) * row_scale * w_scale[None, :]
        # outlier columns in fp against the dequantized weight rows
        w_fp = jnp.where(outlier[:, None], w.astype(jnp.float32)
                         * w_scale[None, :], 0.0)
        y = y_int + a_fp @ w_fp
        y = y.reshape(lead + (w.shape[1],)).astype(a.dtype)
        if bias is not None:
            y = y + rest[i]
        return y

    return apply("llm_int8_linear", f, x, weight, *extras)


for _n in ("weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"):
    register_op(_n, globals()[_n])
