"""Loss layers. Parity: python/paddle/nn/layer/loss.py."""

from __future__ import annotations

from . import functional as F
from .initializer import XavierUniform
from .layer import Layer

__all__ = [
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CosineEmbeddingLoss", "TripletMarginLoss", "HingeEmbeddingLoss",
    "SoftMarginLoss", "MultiMarginLoss", "PoissonNLLLoss", "GaussianNLLLoss",
    "CTCLoss", "RNNTLoss", "AdaptiveLogSoftmaxWithLoss",
]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p, margin=self.margin,
                                   weight=self.weight,
                                   reduction=self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, log_input=self.log_input,
                                  full=self.full, epsilon=self.epsilon,
                                  reduction=self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.fastemit_lambda = blank, fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Hierarchical softmax head (reference: nn.AdaptiveLogSoftmaxWithLoss):
    classes are split by ``cutoffs`` into a frequent-word shortlist scored by
    the head and down-projected tail clusters."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        self.cutoffs = cutoffs + [n_classes]
        self.shortlist_size = cutoffs[0]
        self.n_clusters = len(cutoffs)
        head_size = self.shortlist_size + self.n_clusters
        self.head_weight = self.create_parameter(
            (in_features, head_size), attr=weight_attr,
            default_initializer=XavierUniform())
        self.head_bias = (self.create_parameter((head_size,), attr=bias_attr,
                                                is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter((in_features, hsz), attr=weight_attr,
                                       default_initializer=XavierUniform())
            w2 = self.create_parameter((hsz, osz), attr=weight_attr,
                                       default_initializer=XavierUniform())
            self.add_parameter(f"tail_{i}_proj", w1)
            self.add_parameter(f"tail_{i}_out", w2)
            self.tail_weights.append((w1, w2))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], head_bias=self.head_bias)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label,
                                              weight=self.weight,
                                              reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, margin=self.margin,
            swap=self.swap, reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid output layer (reference: nn.HSigmoidLoss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter((num_classes - 1, 1),
                                          attr=bias_attr, is_bias=True)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=self.bias)
