"""clip_grad_norm_ / clip_grad_value_ (reference:
python/paddle/nn/utils/clip_grad_norm_.py, clip_grad_value_.py): in-place
gradient clipping over a parameter list, returning the total norm."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["clip_grad_norm_", "clip_grad_value_"]


def clip_grad_norm_(parameters, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False) -> Tensor:
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    parameters = list(parameters)  # may be a generator; we iterate twice
    grads = [p.grad._data for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.abs(g).max() for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of order {norm_type} is non-finite; gradients "
            "contain inf/nan (set error_if_nonfinite=False to skip)")
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._set_data((p.grad._data * scale).astype(p.grad._data.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value: float) -> None:
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in list(parameters):
        if p.grad is not None:
            p.grad._set_data(jnp.clip(p.grad._data, -clip_value, clip_value))
