"""``paddle.nn.utils`` (reference: python/paddle/nn/utils/ — weight_norm,
spectral_norm hooks, clip_grad_*, parameters_to_vector)."""

from .weight_norm_hook import remove_weight_norm, weight_norm  # noqa: F401
from .spectral_norm_hook import spectral_norm  # noqa: F401
from .clip_grad import clip_grad_norm_, clip_grad_value_  # noqa: F401
from .transform_parameters import (parameters_to_vector,  # noqa: F401
                                   vector_to_parameters)

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]
