"""weight_norm (reference: python/paddle/nn/utils/weight_norm_hook.py):
reparameterize weight = g * v / ||v|| via a forward-pre hook, keeping g and v
as the trainable parameters."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ..layer import Layer, Parameter

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except_dim(v, dim):
    """dim=None: one Frobenius norm over everything (scalar-shaped); else
    the norm over all axes except ``dim``."""
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v)).reshape((1,) * v.ndim)
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def _compute_weight(g, v, dim):
    def f(g_, v_):
        return g_ * v_ / jnp.maximum(_norm_except_dim(v_, dim), 1e-12)
    return apply("weight_norm", f, g, v)


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0) -> Layer:
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    wdata = w._data
    g0 = _norm_except_dim(wdata, dim)
    g = Parameter(g0, name=(w.name or name) + "_g")
    v = Parameter(wdata, name=(w.name or name) + "_v")
    # replace the plain parameter with the two reparameterized ones
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    setattr(layer, name, _compute_weight(g, v, dim))

    def hook(lyr, inputs):
        setattr(lyr, name, _compute_weight(
            getattr(lyr, name + "_g"), getattr(lyr, name + "_v"), dim))
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_state = (name, dim, handle)
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight") -> Layer:
    state = getattr(layer, "_weight_norm_state", None)
    if state is None or state[0] != name:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    _, dim, handle = state
    handle.remove()
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    w = _compute_weight(g, v, dim)
    layer.add_parameter(name, Parameter(w._data, name=v.name[:-2] if v.name
                                        else name))
    del layer._weight_norm_state
    return layer
