"""parameters_to_vector / vector_to_parameters (reference:
python/paddle/nn/utils/transform_parameters.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["parameters_to_vector", "vector_to_parameters"]


def parameters_to_vector(parameters, name=None) -> Tensor:
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec: Tensor, parameters, name=None) -> None:
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = 1
        for s in p._data.shape:
            n *= int(s)
        p._set_data(data[off:off + n].reshape(p._data.shape)
                    .astype(p._data.dtype))
        off += n
