"""spectral_norm (reference: python/paddle/nn/utils/spectral_norm_hook.py):
weight / sigma_max(weight), sigma estimated by power iteration whose u/v
vectors persist as buffers and update on every forward."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from ...core import random as _random
from ..layer import Layer, Parameter

__all__ = ["spectral_norm"]


def _l2norm(x):
    return x / jnp.maximum(jnp.linalg.norm(x), 1e-12)


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = 0) -> Layer:
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    wdata = w._data
    if dim != 0:
        perm = (dim,) + tuple(i for i in range(wdata.ndim) if i != dim)
    else:
        perm = None
    wm = wdata.transpose(perm) if perm else wdata
    h = wm.shape[0]
    wflat = wm.reshape(h, -1)
    key = _random.default_generator.split_key()
    import jax
    u0 = _l2norm(jax.random.normal(key, (h,), jnp.float32))
    v0 = _l2norm(wflat.T @ u0)

    v_param = Parameter(wdata, name=(w.name or name) + "_orig")
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", v_param)
    layer.register_buffer(name + "_u", Tensor(u0, stop_gradient=True))
    layer.register_buffer(name + "_v", Tensor(v0, stop_gradient=True))

    def compute(lyr):
        worig = getattr(lyr, name + "_orig")
        u = getattr(lyr, name + "_u")
        v = getattr(lyr, name + "_v")

        def f(wd, ud, vd):
            m = wd.transpose(perm) if perm else wd
            flat = m.reshape(m.shape[0], -1)
            uu, vv = ud, vd
            for _ in range(n_power_iterations):
                vv = _l2norm(flat.T @ uu)
                uu = _l2norm(flat @ vv)
            sigma = uu @ flat @ vv
            return wd / jnp.maximum(sigma, eps), uu, vv

        out, uu, vv = apply("spectral_norm", f, worig, u, v)
        u._set_data(uu._data)
        v._set_data(vv._data)
        return out

    setattr(layer, name, compute(layer))

    def hook(lyr, inputs):
        setattr(lyr, name, compute(lyr))
        return None

    layer.register_forward_pre_hook(hook)
    return layer
