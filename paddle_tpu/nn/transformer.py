"""Transformer layers.

Parity: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/DecoderLayer, Transformer). The attention core routes
through ``F.scaled_dot_product_attention`` so the same layer hits the Pallas
flash-attention kernel on TPU when sizes warrant.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.manipulation import concat, reshape, transpose
from . import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


class MultiHeadAttention(Layer):
    Cache = tuple

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, lq = query.shape[0], query.shape[1]
        q = reshape(self.q_proj(query), [b, lq, self.num_heads, self.head_dim])
        k = reshape(self.k_proj(key), [b, key.shape[1], self.num_heads, self.head_dim])
        v = reshape(self.v_proj(value), [b, value.shape[1], self.num_heads, self.head_dim])
        if cache is not None:
            pk, pv = cache
            k = concat([pk, k], axis=1)
            v = concat([pv, v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0,
            training=self.training)
        out = reshape(out, [b, lq, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out

    def gen_cache(self, key, value=None, type=None):
        from ..ops.creation import zeros
        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        v = zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        return (k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is not None:
            src, new_cache = self.self_attn(src, src, src, src_mask, cache)
        else:
            src = self.self_attn(src, src, src, src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return (src, new_cache) if cache is not None else src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            self.encoder = TransformerEncoder(
                enc_layer, num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            self.decoder = TransformerDecoder(
                dec_layer, num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        from ..core.tensor import to_tensor
        import numpy as np
        m = np.triu(np.full((length, length), -np.inf, np.float32), k=1)
        return to_tensor(m)


def _clone_layer(layer: Layer) -> Layer:
    """Fresh copy of a layer with newly initialized parameters (paddle's
    TransformerEncoder deep-copies the prototype layer)."""
    import copy

    new = copy.copy(layer)
    new._parameters = type(layer._parameters)()
    new._sub_layers = type(layer._sub_layers)()
    new._buffers = type(layer._buffers)()
    new._forward_pre_hooks = type(layer._forward_pre_hooks)()
    new._forward_post_hooks = type(layer._forward_post_hooks)()
    from ..core.tensor import Parameter

    for name, p in layer._parameters.items():
        if p is None:
            new._parameters[name] = None
        else:
            # re-draw so the clone is an independent init; when the redraw
            # is skipped (zero-variance or non-float params) the clone must
            # still OWN its array — sharing p._data between two state
            # tensors makes to_static donate the same buffer twice, which
            # the TPU runtime rejects (INVALID_ARGUMENT)
            from ..core.random import default_generator
            import jax
            k = default_generator.split_key()
            std = 0.0
            if jnp.issubdtype(p._data.dtype, jnp.floating):
                std = float(jnp.std(p._data)) if p._data.size > 1 else 0.0
            if std > 0:
                data = jax.random.normal(k, p._data.shape, p._data.dtype) * std
            else:
                data = jnp.array(p._data, copy=True)
            new._parameters[name] = Parameter(data, trainable=p.trainable)
    for name, b in layer._buffers.items():
        new._buffers[name] = (None if b is None
                              else Tensor(jnp.array(b._data, copy=True)))
    for name, sub in layer._sub_layers.items():
        new._sub_layers[name] = _clone_layer(sub)
    return new
