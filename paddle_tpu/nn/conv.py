"""Conv layers. Parity: python/paddle/nn/layer/conv.py."""

from __future__ import annotations

import numpy as np

from . import functional as F
from .initializer import KaimingUniform
from .layer import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose", "Conv1DTranspose",
    "Conv3DTranspose",
]


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, spatial, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, spatial)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + self.kernel_size,
            attr=weight_attr, default_initializer=KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, 2)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        fan_in = in_channels * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + self.kernel_size,
            attr=weight_attr, default_initializer=KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, self.data_format, output_size)


class Conv1DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__()
        self.conv2dt = Conv2DTranspose(in_channels, out_channels,
                                       (1, kernel_size), (1, stride), (0, padding),
                                       (0, output_padding), (1, dilation), groups,
                                       weight_attr, bias_attr)

    def forward(self, x):
        from ..ops.manipulation import squeeze, unsqueeze
        return squeeze(self.conv2dt(unsqueeze(x, 2)), 2)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, 3)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        fan_in = in_channels * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + self.kernel_size,
            attr=weight_attr, default_initializer=KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation,
                                  self.data_format, output_size)
