"""``paddle.nn.functional`` namespace: re-exports the functional op surface.

Parity: python/paddle/nn/functional/__init__.py.
"""

from ..ops.activation import (  # noqa: F401
    relu, relu6, silu, swish, softsign, tanhshrink, mish, hardswish,
    hardsigmoid, log_sigmoid, gelu, softmax, log_softmax, softplus, leaky_relu,
    elu, selu, celu, prelu, hardtanh, hardshrink, softshrink, thresholded_relu,
    glu, maxout, gumbel_softmax,
)
from ..ops.math import sigmoid, tanh  # noqa: F401
from ..ops.nn_ops import (  # noqa: F401
    linear, embedding, dropout, dropout2d, dropout3d, alpha_dropout,
    layer_norm, rms_norm, batch_norm, instance_norm, group_norm,
    local_response_norm, normalize, scaled_dot_product_attention,
    softmax_mask_fuse_upper_triangle,
)
from ..ops.conv_pool import (  # noqa: F401
    conv1d, conv2d, conv3d, conv2d_transpose, max_pool1d, max_pool2d,
    max_pool3d, avg_pool1d, avg_pool2d, avg_pool3d, adaptive_avg_pool1d,
    adaptive_avg_pool2d, adaptive_avg_pool3d, adaptive_max_pool1d,
    adaptive_max_pool2d, adaptive_max_pool3d, conv3d_transpose, interpolate,
    upsample,
    pixel_shuffle, pixel_unshuffle, channel_shuffle, fold, unfold,
)
from ..ops.loss_ops import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy, nll_loss, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    kl_div, hinge_embedding_loss, margin_ranking_loss, cosine_embedding_loss,
    triplet_margin_loss, square_error_cost, log_loss, sigmoid_focal_loss,
)
from ..ops.manipulation import pad  # noqa: F401
from ..ops.indexing import one_hot  # noqa: F401
from ..ops.flash_attention import flash_attention, flash_attn_unpadded  # noqa: F401
from ..ops.nn_ext import (  # noqa: F401
    affine_grid, grid_sample, max_unpool1d, max_unpool2d, max_unpool3d,
    fractional_max_pool2d, fractional_max_pool3d, rrelu, temporal_shift,
    soft_margin_loss, multi_margin_loss, npair_loss, poisson_nll_loss,
    gaussian_nll_loss, margin_cross_entropy, ctc_loss, rnnt_loss,
    adaptive_log_softmax_with_loss, class_center_sample, sparse_attention,
    dice_loss, multi_label_soft_margin_loss,
    triplet_margin_with_distance_loss, hsigmoid_loss, zeropad2d,
    embedding_bag, pairwise_distance, linear_compress, bilinear,
    gather_tree,
)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    from ..ops._helpers import ensure_tensor
    from ..core.tensor import apply
    import jax.numpy as jnp
    label = ensure_tensor(label)
    n = label._data.shape[-1]

    def f(y):
        return (1.0 - epsilon) * y + epsilon / n

    return apply("label_smooth", f, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    from ..ops._helpers import ensure_tensor
    from ..core.tensor import apply
    import jax.numpy as jnp
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)

    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply("cosine_similarity", f, x1, x2)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ..ops._helpers import ensure_tensor
    from ..core.tensor import apply
    import jax.numpy as jnp
    x = ensure_tensor(x)
    if maxlen is None:
        import numpy as np
        maxlen = int(np.asarray(x._data).max())

    def f(lens):
        r = jnp.arange(maxlen)
        return (r[None, :] < lens[..., None]).astype(jnp.dtype(dtype))

    return apply("sequence_mask", f, x, differentiable=False)
