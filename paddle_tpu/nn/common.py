"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample.

Parity: python/paddle/nn/layer/common.py.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import dtype as _dtype
from ..core.tensor import Tensor
from . import functional as F
from .initializer import Constant, Normal, XavierUniform
from .layer import Layer, ParamAttr

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "Flatten", "Identity", "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "Pad1D", "Pad2D", "Pad3D", "CosineSimilarity", "PixelShuffle",
    "PixelUnshuffle", "ChannelShuffle", "Unfold", "Fold",
    "MaxUnPool3D", "FractionalMaxPool2D", "FractionalMaxPool3D",
]


class Linear(Layer):
    """y = xW + b with W of shape (in_features, out_features) — paddle layout
    (upstream: python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierUniform())
        if padding_idx is not None:
            self.weight._set_data(
                self.weight._data.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx,
                           sparse=self.sparse)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadND):
    pass


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class Unflatten(Layer):
    """Reshape one axis into the given shape (reference: paddle.nn.Unflatten)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.unflattened_shape = axis, tuple(int(s) for s in shape)

    def forward(self, x):
        from ..ops.manipulation import reshape
        shp = list(x.shape)
        ax = self.axis if self.axis >= 0 else self.axis + len(shp)
        new = shp[:ax] + list(self.unflattened_shape) + shp[ax + 1:]
        return reshape(x, new)

    def extra_repr(self):
        return f"axis={self.axis}, shape={self.unflattened_shape}"


class FeatureAlphaDropout(Layer):
    """Alpha dropout over whole channels (SELU-preserving statistics)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        import jax
        import jax.numpy as jnp
        from ..core.random import default_generator
        from ..core.tensor import apply

        key = default_generator.split_key()
        p = self.p
        alpha_p = -1.7580993408473766  # -selu_alpha * selu_scale

        def f(a):
            shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
            keep = jax.random.bernoulli(key, 1.0 - p, shape)
            av = 1.0 / jnp.sqrt((alpha_p ** 2 * p + 1.0) * (1.0 - p))
            bv = -av * alpha_p * p
            return (jnp.where(keep, a, alpha_p) * av + bv).astype(a.dtype)

        return apply("feature_alpha_dropout", f, x)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ..ops.nn_ext import pairwise_distance
        return pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                 keepdim=self.keepdim)


class Bilinear(Layer):
    """out[k] = x1 W[k] x2^T + b (reference: paddle.nn.Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter((1, out_features), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ..ops.nn_ext import bilinear as _bilinear
        return _bilinear(x1, x2, self.weight, self.bias)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        from ..ops.manipulation import unsqueeze, squeeze
        k, s, p, osz = self.args
        x4 = unsqueeze(x, 2)
        i4 = unsqueeze(indices, 2)
        if osz is not None:
            osz = (1, int(osz[-1]))  # length is the last entry of any form
        out = F.max_unpool2d(x4, i4, (1, k), (1, s or k), (0, p),
                             output_size=osz)
        return squeeze(out, 2)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, osz = self.args
        return F.max_unpool2d(x, indices, k, s, p, output_size=osz)


class _ZeroPadND(Layer):
    def __init__(self, padding, n_spatial, channels_last, name=None):
        super().__init__()
        self.padding = padding
        self._n = n_spatial
        self._channels_last = channels_last

    def forward(self, x):
        import jax.numpy as jnp
        from ..core.tensor import apply

        pad = self.padding
        if isinstance(pad, int):
            pad = [pad] * (2 * self._n)
        pairs = [(int(pad[2 * i]), int(pad[2 * i + 1]))
                 for i in range(self._n)]
        last = self._channels_last

        def f(a):
            # paddle pad order lists the LAST spatial dim's pair first
            spatial = list(reversed(pairs))
            if last:  # N, spatial..., C
                cfg = [(0, 0)] + spatial + [(0, 0)]
            else:  # N, C, spatial...
                cfg = [(0, 0)] * (a.ndim - self._n) + spatial
            return jnp.pad(a, cfg)

        return apply("zeropad", f, x)


class ZeroPad1D(_ZeroPadND):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, 1, data_format == "NLC")


class ZeroPad2D(_ZeroPadND):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, 2, data_format == "NHWC")


class ZeroPad3D(_ZeroPadND):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, 3, data_format == "NDHWC")


class EmbeddingBag(Layer):
    """Embedding + bag reduction in one lookup (reference: nn.EmbeddingBag)."""

    def __init__(self, num_embeddings, embedding_dim, mode="mean",
                 weight_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierUniform())

    def forward(self, input, offsets=None):
        return F.embedding_bag(input, self.weight, offsets=offsets,
                               mode=self.mode)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
        super().__init__()
        if data_format != "NCDHW":
            raise NotImplementedError("MaxUnPool3D supports NCDHW only")
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)
