"""Pooling layers. Parity: python/paddle/nn/layer/pooling.py."""

from __future__ import annotations

from . import functional as F
from .layer import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D",
    "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool3D",
]


class _Pool(Layer):
    """Shared storage; subclass __init__s carry the upstream-exact positional
    signatures (python/paddle/nn/layer/pooling.py — note upstream's own
    inconsistency: MaxPool* puts return_mask before ceil_mode, AvgPool1D puts
    exclusive before ceil_mode, AvgPool2D/3D put ceil_mode first)."""

    _DEFAULT_FORMAT = "NCHW"

    def _store(self, kernel_size, stride, padding, ceil_mode, data_format):
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format or self._DEFAULT_FORMAT


class MaxPool1D(_Pool):
    _DEFAULT_FORMAT = "NCL"

    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format=None, name=None):
        super().__init__()
        self._store(kernel_size, stride, padding, ceil_mode, data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format=None, name=None):
        super().__init__()
        self._store(kernel_size, stride, padding, ceil_mode, data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class MaxPool3D(_Pool):
    _DEFAULT_FORMAT = "NCDHW"

    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format=None, name=None):
        super().__init__()
        self._store(kernel_size, stride, padding, ceil_mode, data_format)
        self.return_mask = return_mask

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            return_mask=self.return_mask,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool1D(_Pool):
    _DEFAULT_FORMAT = "NCL"

    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, data_format=None, name=None):
        super().__init__()
        self._store(kernel_size, stride, padding, ceil_mode, data_format)
        self.exclusive = exclusive

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format=None,
                 name=None):
        super().__init__()
        self._store(kernel_size, stride, padding, ceil_mode, data_format)
        self.exclusive = exclusive
        self.divisor_override = divisor_override

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            exclusive=self.exclusive,
                            divisor_override=self.divisor_override,
                            data_format=self.data_format)


class AvgPool3D(_Pool):
    _DEFAULT_FORMAT = "NCDHW"

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format=None,
                 name=None):
        super().__init__()
        self._store(kernel_size, stride, padding, ceil_mode, data_format)
        self.exclusive = exclusive
        self.divisor_override = divisor_override

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            exclusive=self.exclusive,
                            divisor_override=self.divisor_override,
                            data_format=self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size,
                                     return_mask=self.return_mask)


class _LPPool(Layer):
    """Power-average pooling: (sum_window |x|^p)^(1/p) (reference:
    paddle.nn.LPPool1D/2D — upstream python/paddle/nn/layer/pooling.py).
    Lowered as avg_pool over |x|^p times the window size, then the p-th
    root (one fused XLA reduce-window, no custom kernel needed)."""

    _ND = 2

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format=None, name=None):
        super().__init__()
        self.norm_type = float(norm_type)
        if self.norm_type == 0:
            raise ValueError("norm_type must be non-zero")
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format or ("NCL" if self._ND == 1 else "NCHW")

    def _window_count(self):
        k = self.kernel_size
        if isinstance(k, int):
            return k ** self._ND
        out = 1
        for v in k:
            out *= v
        return out

    def forward(self, x):
        p = self.norm_type
        n = float(self._window_count())
        # reference semantics: SIGNED x**p (sum can go negative; its p-th
        # root is then nan for odd/fractional p — torch/paddle agree)
        powed = x ** p
        # exclusive=False: avg divides by the FULL kernel size, so avg*n is
        # the true window sum (padding zeros contribute nothing to x**p) —
        # exclusive counting would over-scale partial/padded windows
        if self._ND == 1:
            avg = F.avg_pool1d(powed, self.kernel_size, self.stride,
                               self.padding, exclusive=False,
                               ceil_mode=self.ceil_mode,
                               data_format=self.data_format)
        else:
            avg = F.avg_pool2d(powed, self.kernel_size, self.stride,
                               self.padding, exclusive=False,
                               ceil_mode=self.ceil_mode,
                               data_format=self.data_format)
        return (avg * n) ** (1.0 / p)


class LPPool1D(_LPPool):
    _ND = 1


class LPPool2D(_LPPool):
    _ND = 2
