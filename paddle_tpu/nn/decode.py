"""Seq2seq decoding: ``BeamSearchDecoder`` + ``dynamic_decode``.

Parity surface: python/paddle/nn/decode.py (Decoder/BeamSearchDecoder/
dynamic_decode). TPU notes: generation is a host-driven loop over jitted
cell steps (the per-step compute compiles once; the loop trip count is
data-dependent, which XLA cannot trace) — the same shape the reference's
dynamic decode takes in dygraph.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .layer import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Abstract stepper: initialize() / step() / finalize()."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


_BeamState = namedtuple("_BeamState",
                        ["cell_states", "log_probs", "finished", "lengths"])
_BeamOutput = namedtuple("_BeamOutput",
                         ["scores", "predicted_ids", "parent_ids"])


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference: paddle.nn.BeamSearchDecoder).

    ``cell`` maps (inputs, states) -> (outputs, new_states); ``output_fn``
    projects cell outputs to vocab logits; ``embedding_fn`` embeds token ids
    to the next step's inputs.
    """

    def __init__(self, cell, start_token: int, end_token: int, beam_size: int,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token, self.end_token = int(start_token), int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers (reference API surface) ------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """(B, ...) -> (B*beam, ...) by repeating each row beam_size times."""
        x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        a = x._data
        tiled = jnp.repeat(a[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + a.shape[1:]))

    def _merge(self, a):  # (B, beam, ...) -> (B*beam, ...)
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a):  # (B*beam, ...) -> (B, beam, ...)
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    def _map_states(self, states, fn):
        if isinstance(states, (list, tuple)):
            return type(states)(self._map_states(s, fn) for s in states)
        arr = states._data if isinstance(states, Tensor) else states
        return Tensor(fn(arr))

    # -- Decoder interface ---------------------------------------------------
    def initialize(self, inits):
        """``inits``: cell initial states batched (B, ...)."""
        states = self._map_states(
            inits, lambda a: self._merge(jnp.repeat(a[:, None],
                                                    self.beam_size, axis=1)))
        first = jnp.asarray(states[0]._data if isinstance(states,
                                                          (list, tuple))
                            else states._data)
        batch = first.shape[0] // self.beam_size
        ids = jnp.full((batch, self.beam_size), self.start_token, jnp.int32)
        inputs = self._embed(ids)
        # beam 0 active, others -inf so the first expansion is unique
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        jnp.float32)[None, :], (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        state = _BeamState(states, log_probs, finished,
                           jnp.zeros((batch, self.beam_size), jnp.int32))
        return inputs, state, Tensor(finished)

    def _embed(self, ids):
        flat = Tensor(ids.reshape(-1))
        if self.embedding_fn is not None:
            emb = self.embedding_fn(flat)
            return emb
        return flat

    def step(self, time, inputs, states: _BeamState, **kwargs):
        cell_out, next_cell_states = self.cell(inputs, states.cell_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = cell_out._data.astype(jnp.float32)     # (B*beam, V)
        vocab = logits.shape[-1]
        logp = self._split(jax.nn.log_softmax(logits, axis=-1))  # (B, beam, V)
        # finished beams only extend with end_token at no cost
        fin = states.finished[:, :, None]
        end_onehot = (jnp.arange(vocab) == self.end_token)[None, None, :]
        logp = jnp.where(fin, jnp.where(end_onehot, 0.0, -1e9), logp)
        total = states.log_probs[:, :, None] + logp     # (B, beam, V)
        flat = total.reshape(total.shape[0], -1)
        top_scores, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jnp.int32)   # (B, beam)
        token = (top_idx % vocab).astype(jnp.int32)
        batch = flat.shape[0]
        bi = jnp.arange(batch)[:, None]
        new_finished = jnp.take_along_axis(states.finished, parent, axis=1) \
            | (token == self.end_token)
        new_lengths = jnp.take_along_axis(states.lengths, parent, axis=1) + \
            (~jnp.take_along_axis(states.finished, parent, axis=1)).astype(jnp.int32)

        def reorder(a):
            s = self._split(a)
            g = s[bi, parent]
            return self._merge(g)

        next_states = _BeamState(
            self._map_states(next_cell_states, reorder),
            top_scores, new_finished, new_lengths)
        outputs = _BeamOutput(Tensor(top_scores), Tensor(token),
                              Tensor(parent))
        next_inputs = self._embed(token)
        return outputs, next_states, next_inputs, Tensor(new_finished)

    def finalize(self, outputs: _BeamOutput, final_states, sequence_lengths):
        """Backtrack parent pointers to materialize beams (B, T, beam)."""
        preds = outputs.predicted_ids._data      # (T, B, beam)
        parents = outputs.parent_ids._data
        t_max = preds.shape[0]
        beam = jnp.arange(self.beam_size)[None, :]
        toks = []
        cur = jnp.broadcast_to(beam, parents.shape[1:]).astype(jnp.int32)
        for t in range(t_max - 1, -1, -1):
            toks.append(jnp.take_along_axis(preds[t], cur, axis=1))
            cur = jnp.take_along_axis(parents[t], cur, axis=1)
        ids = jnp.stack(toks[::-1], axis=0)       # (T, B, beam)
        return Tensor(ids), final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder: Decoder, inits=None, max_step_num: Optional[int] = None,
                   output_time_major: bool = False, impute_finished: bool = False,
                   is_test: bool = False, return_length: bool = False,
                   **kwargs):
    """Run ``decoder`` until every sequence finishes or ``max_step_num``.

    Returns (outputs, final_states[, sequence_lengths]).
    """
    inputs, states, finished = decoder.initialize(inits)
    max_steps = int(max_step_num) if max_step_num is not None else 256
    if max_steps <= 0:
        raise ValueError(f"max_step_num must be positive, got {max_steps}")

    def _impute(new, old, mask):
        """Copy ``old`` through where ``mask`` (finished before this step)."""
        if isinstance(new, (list, tuple)):
            return type(new)(_impute(n, o, mask) for n, o in zip(new, old))
        if not isinstance(new, Tensor):
            return new
        m = mask.reshape(mask.shape + (1,) * (new._data.ndim - mask.ndim))
        return Tensor(jnp.where(m, old._data, new._data))

    step_outputs = []
    time = 0
    while time < max_steps:
        prev_states, prev_finished = states, finished
        outputs, states, inputs, finished = decoder.step(time, inputs, states,
                                                         **kwargs)
        if impute_finished and not decoder.tracks_own_finished:
            mask = jnp.asarray(prev_finished._data)
            states = _impute(states, prev_states, mask)
            if hasattr(outputs, "_fields"):
                outputs = type(outputs)(*[_impute(getattr(outputs, f),
                                                  Tensor(jnp.zeros_like(
                                                      getattr(outputs, f)._data)),
                                                  mask)
                                          for f in outputs._fields])
            elif isinstance(outputs, Tensor):
                outputs = Tensor(jnp.where(
                    mask.reshape(mask.shape + (1,) * (outputs._data.ndim -
                                                      mask.ndim)),
                    jnp.zeros_like(outputs._data), outputs._data))
        step_outputs.append(outputs)
        time += 1
        if bool(np.asarray(finished._data).all()):
            break

    if isinstance(step_outputs[0], tuple) and hasattr(step_outputs[0], "_fields"):
        stacked = type(step_outputs[0])(*[
            Tensor(jnp.stack([getattr(o, f)._data for o in step_outputs]))
            for f in step_outputs[0]._fields])
    else:
        stacked = Tensor(jnp.stack([o._data for o in step_outputs]))

    seq_len = getattr(states, "lengths", None)
    final_outputs, final_states = decoder.finalize(stacked, states, seq_len)

    if not output_time_major:
        def to_batch_major(t):
            a = t._data
            return Tensor(jnp.swapaxes(a, 0, 1))
        if isinstance(final_outputs, tuple) and hasattr(final_outputs, "_fields"):
            final_outputs = type(final_outputs)(
                *[to_batch_major(getattr(final_outputs, f))
                  for f in final_outputs._fields])
        else:
            final_outputs = to_batch_major(final_outputs)

    if return_length:
        if seq_len is None:
            raise ValueError(
                "return_length=True needs the decoder's final state to carry "
                "a 'lengths' field (BeamSearchDecoder does); this decoder's "
                "states do not track sequence lengths")
        return final_outputs, final_states, Tensor(seq_len)
    return final_outputs, final_states
