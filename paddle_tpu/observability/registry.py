"""Process-global metrics registry: counters, gauges, histograms.

Parity surface: the reference framework's monitor/stat layer
(paddle/fluid/platform/monitor.h StatRegistry + the python
``paddle.utils.monitor`` counters) — a process-wide, thread-safe registry of
named numeric series that subsystems bump from hot paths and tooling reads
out-of-band. TPU-native design notes:

* metric families are created lazily (``counter()``/``gauge()``/
  ``histogram()`` are get-or-create) so instrumented modules never have to
  coordinate declaration order;
* labeled series live inside the family, keyed by the tuple of label
  values — the Prometheus data model, chosen so the text exposition falls
  out naturally;
* histograms use FIXED bucket boundaries captured at family creation:
  cumulative bucket counts + sum + count, again the Prometheus shape;
* locking is PER FAMILY (each metric carries its own lock; the registry
  lock only guards family creation), so ``snapshot()`` is per-series
  consistent but not atomic across families. Per-op dispatch cost when
  ENABLED is three family locks (ops counter, per-op counter, latency
  histogram), each one dict hit + increment; when DISABLED the dispatch
  hook is uninstalled entirely (see
  ``paddle_tpu/observability/__init__.py``), so the cold path pays
  nothing.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "LogThrottle", "Registry",
           "DEFAULT_LATENCY_BUCKETS"]

# Seconds-scale latency boundaries: 10us .. 10s, roughly x3 per step —
# wide enough to span a CPU elementwise dispatch and a relay-attached
# compiled step in the same family.
DEFAULT_LATENCY_BUCKETS = (
    1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0, 10.0,
)


def _label_key(labelnames: Tuple[str, ...], labels: Dict[str, Any]
               ) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"metric labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """One metric FAMILY: a name plus its labeled series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (), lock: Optional[Any] = None):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock if lock is not None else threading.Lock()
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _zero(self):
        return 0.0

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    def series(self) -> Dict[Tuple[str, ...], Any]:
        """Snapshot of {label-values tuple: value} (values are copies)."""
        with self._lock:
            return {k: (dict(v) if isinstance(v, dict) else v)
                    for k, v in self._series.items()}


class Counter(_Metric):
    """Monotonically increasing count (reference: monitor Int stats)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._series.get(key, 0.0)


class Gauge(_Metric):
    """Point-in-time level (queue depth, node age, bubble fraction)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[key] = float(value)

    def add(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(value)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._series.get(key, 0.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram with fixed boundaries.

    Reads (``series``/``stats``) return
    ``{"buckets": [c_0..c_{B}], "sum": s, "count": n}`` where
    ``buckets[i]`` counts observations <= ``boundaries[i]`` and the final
    slot is the +Inf bucket (== count), the Prometheus layout. Storage is
    per-bucket raw counts; cumulation happens at read time so the write
    path stays one bisect + one increment.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None,
                 lock: Optional[Any] = None):
        super().__init__(name, help, labelnames, lock=lock)
        b = tuple(sorted(float(x) for x in
                         (DEFAULT_LATENCY_BUCKETS if buckets is None
                          else buckets)))
        if not b:
            raise ValueError("histogram needs at least one bucket boundary")
        self.boundaries = b

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        value = float(value)
        # hot path (the per-op dispatch hook lands here): ONE bisect + one
        # slot increment under the lock; raw per-bucket counts are
        # cumulated into the Prometheus shape only at read time
        idx = bisect.bisect_left(self.boundaries, value)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"buckets": [0] * (len(self.boundaries) + 1),
                      "sum": 0.0, "count": 0}
                self._series[key] = st
            st["buckets"][idx] += 1
            st["sum"] += value
            st["count"] += 1

    @staticmethod
    def _cumulate(st: Dict[str, Any]) -> Dict[str, Any]:
        cum, acc = [], 0
        for c in st["buckets"]:
            acc += c
            cum.append(acc)
        return {"buckets": cum, "sum": st["sum"], "count": st["count"]}

    def series(self) -> Dict[Tuple[str, ...], Any]:
        with self._lock:
            return {k: self._cumulate(st) for k, st in self._series.items()}

    def stats(self, **labels) -> Dict[str, Any]:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                return {"buckets": [0] * (len(self.boundaries) + 1),
                        "sum": 0.0, "count": 0}
            return self._cumulate(st)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Registry:
    """Thread-safe collection of metric families, keyed by name.

    ``snapshot()`` returns plain data (no live objects): unlabeled series
    flatten to their scalar (or histogram dict) under the family name;
    labeled series nest under ``{"k=v,...": value}``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as {m.kind}")
                if tuple(labelnames) != m.labelnames:
                    raise ValueError(
                        f"metric {name!r} label mismatch: "
                        f"{tuple(labelnames)} vs {m.labelnames}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        h = self._get_or_create(Histogram, name, help, labelnames,
                                buckets=buckets)
        if buckets is not None:
            want = tuple(sorted(float(x) for x in buckets))
            if want != h.boundaries:
                # boundaries are FIXED at family creation; silently keeping
                # the old ones would drop every sample into +Inf
                raise ValueError(
                    f"histogram {name!r} already registered with buckets "
                    f"{h.boundaries}, requested {want}")
        return h

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def families(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- read-out -----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for m in self.families():
            series = m.series()
            if not m.labelnames:
                if () in series:
                    out[m.name] = series[()]
                continue
            labeled = {}
            for key, val in series.items():
                label_str = ",".join(f"{n}={v}"
                                     for n, v in zip(m.labelnames, key))
                labeled[label_str] = val
            if labeled:
                out[m.name] = labeled
        return out

    def reset(self) -> None:
        """Zero every series; families (names, buckets, labels) survive."""
        for m in self.families():
            m.clear()


class LogThrottle:
    """At-most-one log line per ``interval`` seconds, on a monotonic
    clock that never rewinds. The instrumented subsystems share one
    policy through this class: a failure that repeats every tick keeps
    its COUNTER accurate while the log stays readable — call ``ready()``
    and only emit when it returns True. The first occurrence always
    logs (the initial window is open)."""

    __slots__ = ("interval", "_last")

    def __init__(self, interval: float = 10.0):
        self.interval = float(interval)
        self._last = 0.0

    def ready(self) -> bool:
        now = time.monotonic()
        if now - self._last >= self.interval:
            self._last = now
            return True
        return False


class ScopedTimer:
    """RAII latency sample into a histogram — the metrics analogue of
    ``profiler.RecordEvent``::

        with obs.scoped_timer("train.step_seconds", phase="fwd"):
            ...

    Cheap when observability is disabled: the ``enabled`` probe is taken at
    ``__enter__`` and the exit path short-circuits.
    """

    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: Optional[Histogram], labels: Dict[str, Any]):
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self) -> "ScopedTimer":
        if self._hist is not None:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._hist is not None:
            self._hist.observe(time.perf_counter() - self._t0,
                               **self._labels)
