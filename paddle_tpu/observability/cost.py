"""Program cost accounting — ISSUE 16.

Every compiled-program surface in the repo (the PR 2 eager dispatch
cache, the PR 11 captured whole-step program, the PR 13 bucketed serving
decode/prefill programs) holds a ``jax`` executable whose
``cost_analysis()`` / ``memory_analysis()`` were thrown away until now.
This module is the process-global **program cost registry** that keeps
them: at compile time each new executable is lowered once more against
its argument specs and XLA's modeled flops / bytes-accessed / memory
footprint are recorded under a per-program key. On top of the records it
derives the three numbers ROADMAP item 6(b) says the repo cannot
currently produce:

* a live **HBM ledger** — param/master/moment bytes from the state
  registry, KV pool page bytes from every live
  :class:`~paddle_tpu.serving.kv_cache.PagedKVCache`, the captured
  step's donated-buffer bytes, and headroom against a
  ``PADDLE_TPU_HBM_BYTES`` device model;
* per-program / per-decode-bucket **MFU** and **bandwidth utilization**,
  joined from the cost records and the existing ``train.step_seconds`` /
  ``serving.tpot_seconds`` timing histograms;
* the schema-pinned ``cost`` block in ``bench.py``'s row of record, so
  the next on-chip round pins MFU >= 0.70 against a number the code
  computes rather than a notebook.

Contracts (same shape as the rest of the observability package):

* **Zero per-step host work.** Analysis runs ONCE per compile, under the
  registry lock, from is-None hooks (``jit.to_static._cost_hook``,
  ``core.dispatch_cache._cost_hook``) that stay ``None`` unless
  :func:`install` ran — the ``_op_metrics_hook`` discipline. Disabled
  mode pays one is-None probe per compile, nothing per step.
* **Degrades gracefully.** A backend with no cost model (or an analysis
  call that raises) is COUNTED (``cost.analysis_failures_total``), never
  raised; the record survives with ``model_source="analytic"`` (when an
  analytic estimate exists — the unified ``flops_counter`` fallback) or
  ``"none"``.
* **Records retire** when cache entries evict, programs retrace dead
  state, or their owning ``StaticFunction`` is dropped (weakref
  finalizer) — ``/debug/cost`` lists one record per LIVE program.

Env knobs: ``PADDLE_TPU_COST=on|off`` (default on; the test suite turns
it off suite-wide because capture pays one extra AOT compile per
program), ``PADDLE_TPU_HBM_BYTES`` / ``PADDLE_TPU_PEAK_FLOPS`` /
``PADDLE_TPU_HBM_BW_BYTES`` (device model), and
``PADDLE_TPU_HBM_WARN_FRACTION`` (default 0.10 — the once-per-process
low-headroom warning threshold).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from collections import OrderedDict
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

_log = logging.getLogger("paddle_tpu.observability.cost")

__all__ = [
    "ProgramCostRecord", "mode", "installed", "install", "uninstall",
    "clear", "records", "record_analytic", "device_model", "hbm_ledger",
    "utilization", "debug_doc", "flight_snapshot", "healthz_component",
    "register_kv_cache", "decode_bucket_records", "prefix_sharing_stats",
]

# ---------------------------------------------------------------------------
# metric families (pre-created so capture never races family creation)
# ---------------------------------------------------------------------------
from . import _REGISTRY as _R            # noqa: E402  (same package)

_PROGRAMS = _R.gauge(
    "cost.programs", "live compiled programs with a cost record")
_CAPTURED = _R.counter(
    "cost.programs_captured_total",
    "cost records captured at compile time, by hook site and which cost "
    "model produced the figures", labelnames=("site", "model_source"))
_RETIRED = _R.counter(
    "cost.records_retired_total",
    "cost records dropped (cache eviction / retrace / program death)",
    labelnames=("site",))
_FAILURES = _R.counter(
    "cost.analysis_failures_total",
    "cost/memory analysis calls that returned nothing or raised "
    "(counted, never raised)", labelnames=("reason",))
_FLOPS_G = _R.gauge(
    "cost.program_flops", "XLA-modeled flops of one executable",
    labelnames=("site", "program"))
_BYTES_G = _R.gauge(
    "cost.program_bytes", "XLA-modeled bytes accessed by one executable",
    labelnames=("site", "program"))
_PEAK_G = _R.gauge(
    "cost.program_peak_bytes",
    "modeled memory footprint (argument+output+temp+code) of one "
    "executable", labelnames=("site", "program"))
_MFU_G = _R.gauge(
    "cost.mfu", "achieved MFU: modeled flops / measured seconds / device "
    "peak flops", labelnames=("site", "program"))
_BW_G = _R.gauge(
    "cost.bandwidth_util", "achieved HBM bandwidth fraction: modeled "
    "bytes / measured seconds / device bandwidth",
    labelnames=("site", "program"))
_HBM_G = _R.gauge(
    "cost.hbm_bytes", "live HBM ledger, by component",
    labelnames=("component",))

# ---------------------------------------------------------------------------
# record + registry state
# ---------------------------------------------------------------------------

#: substrings counted in the compiled HLO text — per-program collective
#: counts (optional: big programs may not render; counted best-effort)
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute")


@dataclass
class ProgramCostRecord:
    """One live executable's modeled cost, captured at compile time."""

    key: str                             # registry key (unique per program)
    site: str                            # dispatch | train.step | serving.*
    program: str                         # human label (op name, bucket, ...)
    model_source: str                    # xla | analytic | none
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None     # argument+output+temp+code
    bucket: Optional[int] = None         # serving decode batch bucket
    collectives: Dict[str, int] = field(default_factory=dict)
    captured_at: float = 0.0
    analysis_seconds: float = 0.0


_LOCK = threading.RLock()
_RECORDS: "OrderedDict[str, ProgramCostRecord]" = OrderedDict()
_INSTALLED = False
#: StaticFunction ids with a live weakref finalizer (retire-on-death)
_FINALIZED: set = set()
#: weakrefs to every live PagedKVCache (ledger input)
_KV_CACHES: List[Any] = []
#: the low-headroom warning fires once per process (list so tests can
#: reset the latch without reaching for a global statement)
_HBM_WARN_ONCE = [False]


def mode() -> str:
    """``PADDLE_TPU_COST`` resolved: ``on`` (default) or ``off``."""
    v = os.environ.get("PADDLE_TPU_COST", "on").strip().lower()
    return "off" if v in ("off", "0", "false", "no") else "on"


def installed() -> bool:
    return _INSTALLED


# ---------------------------------------------------------------------------
# device model
# ---------------------------------------------------------------------------

_GIB = 1024 ** 3
#: per-platform defaults (the chip of record is the v5e; the CPU tier
#: models the same chip so the bench's modeled MFU/headroom stay
#: comparable across tiers — override any of the three via env)
_DEVICE_DEFAULTS = {
    "tpu": {"hbm_bytes": 16 * _GIB, "peak_flops": 197e12,
            "hbm_bw_bytes": 819e9},
    "cpu": {"hbm_bytes": 16 * _GIB, "peak_flops": 1e12,
            "hbm_bw_bytes": 50e9},
}


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        _log.warning("ignoring unparseable %s=%r", name, raw)
        return None


def device_model() -> Dict[str, Any]:
    """The modeled device: HBM bytes, peak flop/s, HBM bandwidth."""
    try:
        from .. import device as _device
        platform = _device._accelerator_type()
    except Exception:                                  # pragma: no cover
        platform = "cpu"
    base = _DEVICE_DEFAULTS.get(platform, _DEVICE_DEFAULTS["cpu"])
    hbm = _env_float("PADDLE_TPU_HBM_BYTES")
    peak = _env_float("PADDLE_TPU_PEAK_FLOPS")
    bw = _env_float("PADDLE_TPU_HBM_BW_BYTES")
    return {
        "platform": platform,
        "hbm_bytes": int(hbm) if hbm else base["hbm_bytes"],
        "peak_flops": peak if peak else base["peak_flops"],
        "hbm_bw_bytes": bw if bw else base["hbm_bw_bytes"],
        "source": "env" if (hbm or peak or bw) else "default",
    }


# ---------------------------------------------------------------------------
# capture core
# ---------------------------------------------------------------------------

def _store(rec: ProgramCostRecord) -> None:
    with _LOCK:
        _RECORDS.pop(rec.key, None)
        _RECORDS[rec.key] = rec
        _PROGRAMS.set(len(_RECORDS))
    _CAPTURED.inc(site=rec.site, model_source=rec.model_source)
    if rec.flops is not None:
        _FLOPS_G.set(rec.flops, site=rec.site, program=rec.program)
    if rec.bytes_accessed is not None:
        _BYTES_G.set(rec.bytes_accessed, site=rec.site, program=rec.program)
    if rec.peak_bytes is not None:
        _PEAK_G.set(rec.peak_bytes, site=rec.site, program=rec.program)


def _retire(key: str) -> None:
    with _LOCK:
        rec = _RECORDS.pop(key, None)
        _PROGRAMS.set(len(_RECORDS))
    if rec is not None:
        _RETIRED.inc(site=rec.site)


def _retire_prefix(prefix: str, sf_id: Optional[int] = None) -> None:
    """Retire every record whose key starts with ``prefix`` (an owning
    StaticFunction died, taking all its per-signature programs)."""
    with _LOCK:
        if sf_id is not None:
            _FINALIZED.discard(sf_id)
        dead = [k for k in _RECORDS if k.startswith(prefix)]
    for k in dead:
        _retire(k)


def _capture(key: str, site: str, program: str, lower_fn: Callable[[], Any],
             *, bucket: Optional[int] = None,
             analytic_flops: Optional[float] = None) -> ProgramCostRecord:
    """Lower+compile once, harvest XLA's cost/memory model, store the
    record. Never raises: every analysis failure is counted and the
    record degrades to the analytic fallback (or ``model_source="none"``).
    """
    t0 = time.perf_counter()
    flops = bytes_accessed = None
    mem: Dict[str, Optional[int]] = {}
    collectives: Dict[str, int] = {}
    compiled = None
    try:
        compiled = lower_fn().compile()
    except Exception as e:
        _FAILURES.inc(reason="lower_error")
        _log.debug("cost: lowering %s failed: %s", program, e)
    if compiled is not None:
        try:
            ca = compiled.cost_analysis()
            # jax 0.4.x returns a one-dict list; newer builds a plain dict;
            # a backend without a cost model returns None/empty
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if ca:
                if ca.get("flops") is not None:
                    flops = float(ca["flops"])
                if ca.get("bytes accessed") is not None:
                    bytes_accessed = float(ca["bytes accessed"])
            if flops is None:
                _FAILURES.inc(reason="no_cost_model")
        except Exception as e:
            _FAILURES.inc(reason="cost_analysis")
            _log.debug("cost: cost_analysis(%s) failed: %s", program, e)
        try:
            ms = compiled.memory_analysis()
            mem = {
                "argument_bytes": int(ms.argument_size_in_bytes),
                "output_bytes": int(ms.output_size_in_bytes),
                "temp_bytes": int(ms.temp_size_in_bytes),
                "generated_code_bytes": int(ms.generated_code_size_in_bytes),
            }
        except Exception as e:
            _FAILURES.inc(reason="memory_analysis")
            _log.debug("cost: memory_analysis(%s) failed: %s", program, e)
        try:
            txt = compiled.as_text()
            for opname in _COLLECTIVE_OPS:
                n = txt.count(opname + "(") + txt.count(opname + "-start(")
                if n:
                    collectives[opname] = n
        except Exception:
            pass                          # collective counts are optional
    source = "xla"
    if flops is None:
        if analytic_flops is not None:
            flops, source = float(analytic_flops), "analytic"
        else:
            source = "none"
    peak = None
    if mem:
        peak = sum(v for v in mem.values() if v is not None)
    rec = ProgramCostRecord(
        key=key, site=site, program=program, model_source=source,
        flops=flops, bytes_accessed=bytes_accessed,
        peak_bytes=peak, bucket=bucket, collectives=collectives,
        captured_at=time.time(),
        analysis_seconds=time.perf_counter() - t0, **mem)
    _store(rec)
    return rec


def record_analytic(program: str, flops: float, *, site: str = "analytic",
                    bytes_accessed: Optional[float] = None) -> None:
    """Register an analytic (non-XLA) estimate — the unified
    ``flops_counter`` path feeds per-network totals through here so the
    ``cost.model_source{analytic}`` series reflects them."""
    rec = ProgramCostRecord(
        key=f"analytic:{site}:{program}", site=site, program=program,
        model_source="analytic", flops=float(flops),
        bytes_accessed=bytes_accessed, captured_at=time.time())
    _store(rec)


# ---------------------------------------------------------------------------
# hooks (installed into the hot modules' is-None globals)
# ---------------------------------------------------------------------------

def _spec_of(a) -> Any:
    """ShapeDtypeStruct for one array, preserving a NamedSharding when the
    executable was built against one (same guard as to_static's donation
    spec builder — other sharding kinds re-derive on compile)."""
    import jax
    sh = getattr(a, "sharding", None)
    if sh is not None and not isinstance(
            sh, getattr(jax.sharding, "NamedSharding", ())):
        sh = None
    return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)


def _sf_prefix(sf, cache_key) -> str:
    return f"sf:{id(sf)}:{abs(hash(cache_key)):x}:"


def _on_static_build(event: str, sf, **kw) -> None:
    """``jit.to_static._cost_hook``: event "build" fires once per NEW
    (cache entry, arg aval signature) pair — one entry's jax.jit
    respecializes per input shape, so each serving bucket lands its own
    record — with the jitted callable + specs captured before donation
    consumed the buffers. Event "retire" fires on a dead-state retrace
    and drops every signature's record for that entry."""
    if event == "retire":
        _retire_prefix(_sf_prefix(sf, kw["key"]))
        return
    if event != "build":
        return
    jitted, state_specs, arg_specs = (kw["jitted"], kw["state_specs"],
                                      kw["arg_specs"])
    site = getattr(sf, "cost_site", None) or "jit"
    label = getattr(sf, "cost_label", None) or getattr(
        getattr(sf, "_fn", None), "__name__", "program")
    bucket = None
    shape0 = getattr(arg_specs[0], "shape", None) if arg_specs else None
    if site == "serving.decode" and shape0:
        bucket = int(shape0[0])
        label = f"{label}[b={bucket}]"
    elif site == "serving.prefill" and shape0 is not None and len(shape0) > 1:
        label = f"{label}[len={int(shape0[1])}]"
    sid = id(sf)
    with _LOCK:
        register_finalizer = sid not in _FINALIZED
        if register_finalizer:
            _FINALIZED.add(sid)
    if register_finalizer:
        weakref.finalize(sf, _retire_prefix, f"sf:{sid}:", sid)
    key = _sf_prefix(sf, kw["key"]) + f"{abs(hash(kw.get('sig'))):x}"
    _capture(key, site, label,
             lambda: jitted.lower(state_specs, arg_specs), bucket=bucket,
             analytic_flops=getattr(sf, "cost_analytic_flops", None))


def _dispatch_key(key) -> str:
    return f"op:{abs(hash(key)):x}"


def _on_dispatch_event(event: str, key, **kw) -> None:
    """``core.dispatch_cache._cost_hook``: "store" fires from
    ``core.tensor._apply_cached`` right after a fresh entry lands (the
    run arrays are still in scope for spec building); "evict" fires per
    LRU/configure eviction; "clear" on ``cache_clear``."""
    if event == "store":
        entry, arrays = kw["entry"], kw["arrays"]
        specs = [_spec_of(a) for a in arrays]
        _capture(_dispatch_key(key), "dispatch", str(kw.get("op", "op")),
                 lambda: entry.fwd.lower(*specs))
    elif event == "evict":
        _retire(_dispatch_key(key))
    elif event == "clear":
        with _LOCK:
            dead = [k for k, r in _RECORDS.items() if r.site == "dispatch"]
        for k in dead:
            _retire(k)


def install() -> None:
    """Install the compile-time capture hooks (no-op when
    ``PADDLE_TPU_COST=off``). Called from ``observability.enable()``."""
    global _INSTALLED
    if mode() == "off":
        return
    with _LOCK:
        import importlib
        from ..core import dispatch_cache as _dcache_mod
        # NOT ``from ..jit import to_static``: the jit package re-exports
        # the decorator under the submodule's name, shadowing the module
        _ts_mod = importlib.import_module("paddle_tpu.jit.to_static")
        _dcache_mod._cost_hook = _on_dispatch_event
        _ts_mod._cost_hook = _on_static_build
        _INSTALLED = True


def uninstall() -> None:
    """Remove the hooks; records remain readable until :func:`clear`."""
    global _INSTALLED
    with _LOCK:
        import sys
        dc = sys.modules.get("paddle_tpu.core.dispatch_cache")
        ts = sys.modules.get("paddle_tpu.jit.to_static")
        if dc is not None:
            dc._cost_hook = None
        if ts is not None:
            ts._cost_hook = None
        _INSTALLED = False


def clear() -> None:
    """Drop every record (test isolation seam; wired into
    ``observability.reset()``)."""
    with _LOCK:
        _RECORDS.clear()
        _PROGRAMS.set(0)


def records(site: Optional[str] = None) -> List[Dict[str, Any]]:
    """Plain-data view of the live records, insertion-ordered."""
    with _LOCK:
        recs = list(_RECORDS.values())
    return [asdict(r) for r in recs if site is None or r.site == site]


def decode_bucket_records() -> Dict[int, Dict[str, Any]]:
    """{batch bucket: record} for the live serving decode programs — the
    bench's measured-bytes source for the paged_attention block."""
    out: Dict[int, Dict[str, Any]] = {}
    for r in records(site="serving.decode"):
        if r.get("bucket") is not None:
            out[int(r["bucket"])] = r
    return out


def register_kv_cache(kv) -> None:
    """Track a live PagedKVCache's pool/scales bytes in the HBM ledger
    (weakly: a dropped engine drops its pool from the ledger)."""
    with _LOCK:
        _KV_CACHES[:] = [r for r in _KV_CACHES if r() is not None]
        _KV_CACHES.append(weakref.ref(kv))


def prefix_sharing_stats() -> List[Dict[str, Any]]:
    """Per-live-pool prefix-sharing counters (ISSUE 17): pages in use /
    idle / high-water, the shared-page ratio, and the prefix-index hit
    rate — one row per registered :class:`PagedKVCache`. A page mapped by
    N slots appears here as sharing, never as N× bytes: the HBM ledger
    prices ``pool.nbytes`` (physical pages), so refcounts cannot inflate
    it."""
    with _LOCK:
        kvs = [r() for r in _KV_CACHES]
    rows: List[Dict[str, Any]] = []
    for kv in kvs:
        if kv is None:
            continue
        stats = getattr(kv, "prefix_stats", None)
        if stats is None:
            continue
        try:
            rows.append(stats())
        except Exception as e:             # pragma: no cover - defensive
            rows.append({"error": str(e)})
    return rows


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------

def warn_fraction() -> float:
    v = _env_float("PADDLE_TPU_HBM_WARN_FRACTION")
    return 0.10 if v is None else v


def hbm_ledger() -> Dict[str, Any]:
    """The live HBM ledger: what is resident (state registry + KV pools),
    what the programs need on top (max modeled temp bytes), and the
    headroom against the device model. Pure read — walks live objects,
    no device work."""
    from ..core import tensor as _tensor_mod
    param = master = moment = other = 0
    for t in _tensor_mod._state_registry.alive():
        data = getattr(t, "_data", None)
        nb = int(getattr(data, "nbytes", 0) or 0)
        name = getattr(t, "name", "") or ""
        if isinstance(t, _tensor_mod.Parameter):
            param += nb
        elif name.endswith("_master"):
            master += nb
        elif "moment" in name or name.startswith("fused_"):
            moment += nb
        else:
            other += nb
    kv_pool = 0
    with _LOCK:
        kvs = [r() for r in _KV_CACHES]
    for kv in kvs:
        if kv is None:
            continue
        kv_pool += int(getattr(getattr(kv, "pool", None), "nbytes", 0) or 0)
        kv_pool += int(getattr(getattr(kv, "scales", None), "nbytes", 0) or 0)
    donated = 0
    g = _R.get("train.capture_donated_bytes")
    if g is not None:
        try:
            donated = int(g.value())
        except Exception:
            donated = 0
    with _LOCK:
        temps = [r.temp_bytes for r in _RECORDS.values()
                 if r.temp_bytes is not None]
    program_temp_peak = max(temps) if temps else 0
    dev = device_model()
    state_total = param + master + moment + other
    peak_hbm = state_total + kv_pool + program_temp_peak
    headroom = dev["hbm_bytes"] - peak_hbm
    frac = headroom / dev["hbm_bytes"] if dev["hbm_bytes"] else 0.0
    ledger = {
        "param_bytes": param, "master_bytes": master,
        "moment_bytes": moment, "other_state_bytes": other,
        "state_bytes_total": state_total, "kv_pool_bytes": kv_pool,
        "donated_bytes": donated, "program_temp_peak_bytes":
        program_temp_peak, "hbm_bytes": dev["hbm_bytes"],
        "peak_hbm_bytes": peak_hbm, "headroom_bytes": headroom,
        "headroom_fraction": frac,
    }
    for comp in ("param_bytes", "master_bytes", "moment_bytes",
                 "other_state_bytes", "kv_pool_bytes",
                 "program_temp_peak_bytes", "peak_hbm_bytes",
                 "headroom_bytes"):
        _HBM_G.set(ledger[comp], component=comp[:-len("_bytes")])
    fire_warn = False
    if frac < warn_fraction():
        with _LOCK:
            if not _HBM_WARN_ONCE[0]:
                _HBM_WARN_ONCE[0] = True
                fire_warn = True
    if fire_warn:
        _log.warning(
            "HBM headroom %.1f%% below the %.0f%% warn threshold: modeled "
            "peak %d bytes vs %d device bytes (state %d + kv %d + program "
            "temps %d) — set PADDLE_TPU_HBM_BYTES if the device model is "
            "wrong", 100 * frac, 100 * warn_fraction(), peak_hbm,
            dev["hbm_bytes"], state_total, kv_pool, program_temp_peak)
    return ledger


# ---------------------------------------------------------------------------
# utilization join (cost records x timing histograms)
# ---------------------------------------------------------------------------

def _hist_mean(name: str) -> Optional[float]:
    """Mean of every sample across ALL label series of one histogram
    family, or None when the family has no samples."""
    h = _R.get(name)
    if h is None:
        return None
    total = count = 0.0
    for st in h.series().values():
        total += st["sum"]
        count += st["count"]
    return (total / count) if count else None


def utilization() -> List[Dict[str, Any]]:
    """Join the live cost records against the measured timing histograms:
    ``train.step_seconds`` prices the captured step, ``serving.tpot_seconds``
    prices each decode bucket (TPOT ~ one decode step). Sets the
    ``cost.mfu`` / ``cost.bandwidth_util`` gauges and returns the rows."""
    step_s = _hist_mean("train.step_seconds")
    tpot_s = _hist_mean("serving.tpot_seconds")
    dev = device_model()
    rows: List[Dict[str, Any]] = []
    for r in records():
        secs = None
        if r["site"] == "train.step":
            secs = step_s
        elif r["site"] == "serving.decode":
            secs = tpot_s
        if not secs:
            continue
        mfu = bw = None
        if r["flops"]:
            mfu = r["flops"] / (secs * dev["peak_flops"])
            _MFU_G.set(mfu, site=r["site"], program=r["program"])
        if r["bytes_accessed"]:
            bw = r["bytes_accessed"] / (secs * dev["hbm_bw_bytes"])
            _BW_G.set(bw, site=r["site"], program=r["program"])
        if mfu is None and bw is None:
            continue
        rows.append({"key": r["key"], "site": r["site"],
                     "program": r["program"], "bucket": r["bucket"],
                     "seconds": secs, "mfu": mfu, "bandwidth_util": bw})
    return rows


# ---------------------------------------------------------------------------
# operator surfaces: /debug/cost, flight dumps, /healthz
# ---------------------------------------------------------------------------

def debug_doc() -> Dict[str, Any]:
    """The ``/debug/cost`` document: one record per live compiled
    program, the HBM ledger, the measured-utilization join, and the
    device model they are priced against."""
    try:
        hbm: Any = hbm_ledger()
    except Exception as e:                             # pragma: no cover
        hbm = {"error": str(e)}
    return {
        "pid": os.getpid(), "mode": mode(), "installed": installed(),
        "device": device_model(), "records": records(),
        "hbm": hbm, "utilization": utilization(),
        "prefix_sharing": prefix_sharing_stats(),
    }


def flight_snapshot() -> Dict[str, Any]:
    """Cost snapshot embedded in flight-recorder dumps. NEVER raises —
    a post-mortem must not die collecting its own context."""
    if not installed():
        # chaos paths dump a lot; don't walk the live-tensor registry
        # per dump unless the operator opted into cost accounting —
        # but the prefix-index counters are cheap dict reads and a
        # post-mortem of an eviction storm needs them, so they ride
        # along in the dump tail unconditionally
        return {"mode": "off", "prefix_sharing": prefix_sharing_stats()}
    try:
        return {"records": records(), "hbm": hbm_ledger(),
                "prefix_sharing": prefix_sharing_stats()}
    except Exception as e:
        return {"error": str(e)}


def healthz_component() -> Optional[Dict[str, Any]]:
    """The 503-independent ``hbm`` component for ``/healthz``: ledger
    bytes + headroom detail. ``ok`` is always True — low headroom warns
    (once) but never takes the process out of rotation.

    Returns None when cost accounting is not installed: /healthz is the
    router's rotation signal and may be polled hot, so it must not pay
    a live-tensor registry walk unless the operator opted in."""
    if not installed():
        return None
    try:
        led = hbm_ledger()
    except Exception:
        return None
    return {
        "ok": True, "stale": False,
        "hbm_bytes": led["hbm_bytes"],
        "peak_hbm_bytes": led["peak_hbm_bytes"],
        "state_bytes_total": led["state_bytes_total"],
        "kv_pool_bytes": led["kv_pool_bytes"],
        "headroom_bytes": led["headroom_bytes"],
        "headroom_fraction": led["headroom_fraction"],
        "warn": led["headroom_fraction"] < warn_fraction(),
    }
