"""``paddle_tpu.observability`` — framework-wide metrics & telemetry.

Answers "what is the runtime doing right now" without a profiler session:
op-dispatch rates and latency, jit trace/compile/cache-hit counts, PS RPC
retries and failovers, pipeline step time and bubble fraction, elastic
store health, dataloader queue depth and wait time.

Reference parity: the monitor/stat surface (paddle/fluid/platform/
monitor.h StatRegistry, ``paddle.utils.monitor``); exporters follow the
Prometheus data model instead of the reference's bespoke dump because the
north-star deployment (ROADMAP) scrapes.

Usage::

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs

    obs.enable()                      # installs the dispatch hook
    ... train ...
    snap = obs.snapshot()             # {"dispatch.ops_total": 1234, ...}
    print(obs.prometheus_text())      # scrape document
    obs.reset(); obs.disable()

Naming convention (enforced by habit, asserted in tests for the built-ins):
``<subsystem>.<noun>_<unit>`` with counters suffixed ``_total``, histograms
suffixed ``_seconds`` (SI base units), gauges plain nouns — e.g.
``dispatch.ops_total``, ``ps.rpc_retries_total``,
``dataloader.wait_seconds``, ``pipeline.bubble_fraction``.

Since ISSUE 12 the package also owns the TRACING surface: ``trace``
(span trees, the Chrome trace-event exporter, and the always-on crash
flight recorder — see :mod:`paddle_tpu.observability.trace`) and ``http``
(the ``/metrics`` + ``/healthz`` + ``/debug`` scrape endpoint behind
``PADDLE_TPU_OBS_HTTP_PORT`` — :mod:`paddle_tpu.observability.http`).

Zero-overhead contract: when disabled (the default), the op-dispatch seam
carries NO observability work — ``core.tensor._op_metrics_hook`` is
``None`` and ``apply()`` only performs the same is-None probe it already
performed for the profiler. Module-level helpers (``inc``/``observe``/
``set_gauge``/``scoped_timer``) short-circuit on one global bool, cheap
enough for per-call (not per-op) seams like jit cache lookups and RPC
issue paths.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence

from .registry import (Counter, Gauge, Histogram, LogThrottle, Registry,
                       ScopedTimer, DEFAULT_LATENCY_BUCKETS)
from . import trace  # noqa: F401  (ISSUE 12: spans + flight recorder;
#                      imported BEFORE export, which shares its envelope)
from .export import (StepTelemetryWriter, parse_prometheus_text,
                     prometheus_text as _prom_text, read_jsonl)

__all__ = [
    "Counter", "Gauge", "Histogram", "LogThrottle", "Registry",
    "StepTelemetryWriter",
    "DEFAULT_LATENCY_BUCKETS",
    "enable", "disable", "enabled", "default_registry",
    "counter", "gauge", "histogram",
    "inc", "set_gauge", "observe", "scoped_timer",
    "snapshot", "reset", "prometheus_text", "parse_prometheus_text",
    "read_jsonl",
    "trace", "cost",
]

_REGISTRY = Registry()
_ENABLED = False
_LOCK = threading.Lock()

# ISSUE 16: the program cost registry (submodule `cost`) shares this
# registry's metric families. It is imported lazily inside
# enable()/disable()/reset() — a module-scope `from . import cost` here
# would be a load-bearing import cycle (cost reads _REGISTRY back out of
# this package at ITS import time), and nothing needs the submodule
# before the first enable().


def default_registry() -> Registry:
    return _REGISTRY


def enabled() -> bool:
    return _ENABLED


# -- built-in dispatch instrumentation ---------------------------------------
# Families are pre-created so the hot hook never takes the registry lock.
_DISPATCH_OPS = _REGISTRY.counter(
    "dispatch.ops_total", "ops dispatched through core.tensor.apply")
_DISPATCH_BY_OP = _REGISTRY.counter(
    "dispatch.ops_by_name_total", "per-op dispatch counts", labelnames=("op",))
_DISPATCH_LATENCY = _REGISTRY.histogram(
    "dispatch.latency_seconds", "host-side latency of one eager dispatch")

# eager compiled-op cache (core/dispatch_cache.py): hit/miss/compile/evict
# plus bypasses labeled by reason (capture, symbolic_input, closure_array,
# static_unhashable, untraceable)
_CACHE_HITS = _REGISTRY.counter(
    "dispatch.cache_hits_total", "eager-cache dispatches served compiled")
_CACHE_MISSES = _REGISTRY.counter(
    "dispatch.cache_misses_total", "eager-cache probes that found no entry")
_CACHE_COMPILES = _REGISTRY.counter(
    "dispatch.cache_compiles_total", "signatures compiled into the cache")
_CACHE_EVICTIONS = _REGISTRY.counter(
    "dispatch.cache_evictions_total", "LRU evictions from the eager cache")
_CACHE_BYPASS = _REGISTRY.counter(
    "dispatch.cache_bypass_total", "dispatches that bypassed the eager cache",
    labelnames=("reason",))


def _dispatch_hook(op_name: str, t0: float, t1: float) -> None:
    """Installed into ``core.tensor._op_metrics_hook`` while enabled."""
    _DISPATCH_OPS.inc()
    _DISPATCH_BY_OP.inc(op=op_name)
    _DISPATCH_LATENCY.observe(t1 - t0)


def _cache_hook(kind: str, reason) -> None:
    """Installed into ``core.dispatch_cache._obs_hook`` while enabled."""
    if kind == "hit":
        _CACHE_HITS.inc()
    elif kind == "miss":
        _CACHE_MISSES.inc()
    elif kind == "compile":
        _CACHE_COMPILES.inc()
    elif kind == "evict":
        _CACHE_EVICTIONS.inc()
    else:
        _CACHE_BYPASS.inc(reason=reason or "other")


def enable() -> None:
    """Turn metrics collection on and install the dispatch hooks."""
    global _ENABLED
    with _LOCK:
        _ENABLED = True
        from ..core import tensor as _tensor_mod
        from ..core import dispatch_cache as _dcache_mod
        _tensor_mod._op_metrics_hook = _dispatch_hook
        _dcache_mod._obs_hook = _cache_hook
    # compile-time cost capture rides the same switch (its own is-None
    # hooks in to_static/dispatch_cache; no-op under PADDLE_TPU_COST=off)
    from . import cost
    cost.install()


def disable() -> None:
    """Stop collecting; collected values remain readable."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False
        from ..core import tensor as _tensor_mod
        from ..core import dispatch_cache as _dcache_mod
        _tensor_mod._op_metrics_hook = None
        _dcache_mod._obs_hook = None
    from . import cost
    cost.uninstall()


# -- family accessors (get-or-create on the default registry) ----------------
def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _REGISTRY.histogram(name, help, labelnames, buckets)


# -- cheap instrumentation helpers (no-ops while disabled) -------------------
def _check_labels(labels) -> None:
    # ``value`` is positional-only on the helpers: obs.inc("m", value=5)
    # would otherwise land here as a bogus {value="5"} label on an
    # increment of 1 — silently the wrong metric. (``name`` stays legal
    # as a label: the metric name cannot be passed by keyword at all, so
    # name=... is always an intentional label, e.g. the profiler bridge's
    # record_event_seconds{name=...}.)
    if "value" in labels:
        # every production call site passes a literal label set, so this
        # TypeError is unreachable at runtime from the serving/training
        # entry roots — it exists to fail developer mistakes loudly in
        # tier-1, not as part of any typed failure surface
        raise TypeError(  # graft-lint: disable=exception-contract
            "'value' is positional-only — obs.inc(name, amount, **labels); "
            "pass the amount positionally, not as a label")


def inc(name: str, value: float = 1.0, /, **labels) -> None:
    if not _ENABLED:
        return
    _check_labels(labels)
    _REGISTRY.counter(name, labelnames=tuple(sorted(labels))).inc(value, **labels)


def set_gauge(name: str, value: float, /, **labels) -> None:
    if not _ENABLED:
        return
    _check_labels(labels)
    _REGISTRY.gauge(name, labelnames=tuple(sorted(labels))).set(value, **labels)


def observe(name: str, value: float, /, **labels) -> None:
    if not _ENABLED:
        return
    _check_labels(labels)
    _REGISTRY.histogram(name, labelnames=tuple(sorted(labels))).observe(value,
                                                               **labels)


def scoped_timer(name: str, /, **labels) -> ScopedTimer:
    """``with obs.scoped_timer("train.step_seconds", phase="fwd"): ...``
    — observes a latency sample when enabled, free when disabled. Label
    sets are fixed per family: time an EXISTING built-in metric only with
    its declared labels (e.g. ``ps.rpc_seconds`` is unlabeled)."""
    if not _ENABLED:
        return ScopedTimer(None, {})
    return ScopedTimer(_REGISTRY.histogram(name, labelnames=tuple(sorted(labels))),
                       labels)


# -- read-out ----------------------------------------------------------------
def snapshot() -> Dict[str, Any]:
    """Plain-data view of every collected series (works while disabled)."""
    return _REGISTRY.snapshot()


def reset() -> None:
    """Zero every series (metric families survive) and drop cost
    records; test isolation seam."""
    _REGISTRY.reset()
    from . import cost
    cost.clear()


def prometheus_text(registry: Optional[Registry] = None) -> str:
    return _prom_text(registry if registry is not None else _REGISTRY)
