"""The scrape endpoint: ``/metrics`` + ``/healthz`` + ``/debug`` (ISSUE 12),
plus the stdlib HTTP scaffolding the serving front door reuses (ISSUE 15).

Prometheus text export existed since PR 1 only as an in-process function;
the multi-replica front door (ROADMAP item 2) routes on queue-depth/
queue-wait series it has to SCRAPE. This module is the missing surface: a
stdlib ``ThreadingHTTPServer`` (no new dependencies) serving

* ``GET /metrics``       — ``observability.prometheus_text()`` (the
  exposition format scrapers expect);
* ``GET /healthz``       — liveness from the :func:`trace.heartbeat`
  beacons the engine/supervisor step loops and watchdog poll threads
  ping: 200 while every beacon is fresh, 503 once one goes stale (a loop
  thread wedged inside a compiled call stops beating). Since ISSUE 15
  each component carries an explicit ``stale`` bit next to ``ok``, and a
  multi-replica process reports one ``serving.engine.<replica>`` beacon
  per engine — the router's per-replica health detail, not a single
  process-global staleness bit;
* ``GET /debug/flight``  — the flight recorder's last-N-events snapshot
  (the live view of what a crash dump would contain);
* ``GET /debug/trace``   — the current trace buffer as Chrome trace-event
  JSON (save it, open in Perfetto).

Opt-in wiring: the serving engine and the training supervisor call
:func:`maybe_serve_from_env` — set ``PADDLE_TPU_OBS_HTTP_PORT`` and the
process-global server starts once (port 0 = ephemeral, reported in the
log and on ``server.port``); unset, serving/training pay nothing.

Scaffolding sharing (ISSUE 15): :class:`QuietJSONHandler` (the
``_send``/``_send_json`` + quiet-log handler base) and :class:`ServerHost`
(bind read-back + daemon ``serve_forever`` thread + bounded ``close``)
are the pieces ``paddle_tpu.serving.http`` builds its front door on — one
copy of the stdlib-threaded server plumbing, two endpoints. Each endpoint
still constructs its own ``ThreadingHTTPServer`` subclass with a literal
handler class so graft-lint's thread-root discovery keeps seeing every
``do_*`` method as an HTTP-handler thread root.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import trace as _trace

__all__ = ["QuietJSONHandler", "ServerHost", "ObsHTTPServer",
           "start_http_server", "maybe_serve_from_env"]

_log = logging.getLogger(__name__)


class QuietJSONHandler(BaseHTTPRequestHandler):
    """Shared handler base: quiet request logging (scrapers and token
    streams poll — per-request stderr lines would drown the process log)
    plus the byte/JSON response helpers both endpoints use."""

    server_version = "paddle-tpu/1"

    def log_message(self, fmt, *args):
        _log.debug("http: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str,
              headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc,
                   headers: Optional[dict] = None) -> None:
        self._send(code, json.dumps(doc, default=str).encode("utf-8"),
                   "application/json", headers)


class ServerHost:
    """One bound stdlib HTTP server on a daemon ``serve_forever`` thread.

    Owns the scaffolding every endpoint repeats: ``daemon_threads`` (a
    wedged handler must not block process exit), the ephemeral-port
    read-back (``port=0`` is the test/fleet-local pattern — read the real
    port from ``.port``), and a bounded ``close()`` (shutdown + join).
    The caller constructs the ``ThreadingHTTPServer`` itself — the literal
    handler class at the ctor keeps graft-lint's httpd thread-root
    discovery working — and hands it here to run."""

    def __init__(self, httpd: ThreadingHTTPServer, thread_name: str):
        httpd.daemon_threads = True
        self._httpd = httpd
        self.host, self.port = httpd.server_address[:2]
        self._thread = threading.Thread(
            target=httpd.serve_forever, name=thread_name, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


class _Handler(QuietJSONHandler):
    server_version = "paddle-tpu-obs/1"

    def do_GET(self):   # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                from . import prometheus_text
                self._send(200, prometheus_text().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                doc = _trace.health()
                self._send_json(200 if doc["status"] == "ok" else 503, doc)
            elif path == "/debug/flight":
                self._send_json(200, {
                    "pid": os.getpid(),
                    "capacity": _trace.flight_recorder().capacity,
                    "events": _trace.flight_recorder().snapshot()})
            elif path == "/debug/trace":
                self._send_json(200, _trace.export_chrome())
            elif path == "/debug/cost":
                from . import cost as _cost
                self._send_json(200, _cost.debug_doc())
            else:
                self._send_json(404, {"error": "not found", "routes": [
                    "/metrics", "/healthz", "/debug/flight",
                    "/debug/trace", "/debug/cost"]})
        except (BrokenPipeError, ConnectionResetError):
            pass  # why: the scraper hung up mid-response; nothing to serve
        except Exception:
            _log.exception("obs http: handler failed for %s", self.path)
            try:
                self._send_json(500, {"error": "internal"})
            except OSError:
                pass  # why: the response socket is already gone


class ObsHTTPServer(ServerHost):
    """One scrape endpoint on a daemon thread. ``port=0`` binds an
    ephemeral port (read it back from ``.port`` — the test/fleet-local
    pattern); ``close()`` shuts the listener down."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        super().__init__(ThreadingHTTPServer((host, port), _Handler),
                         thread_name="paddle-tpu-obs-http")


def start_http_server(port: int = 0,
                      host: str = "127.0.0.1") -> ObsHTTPServer:
    """Start a scrape endpoint explicitly (tests, embedders)."""
    return ObsHTTPServer(port=port, host=host)


_GLOBAL: Optional[ObsHTTPServer] = None
_DISABLED = False        # a failed opt-in latches off: warn once, not per
_GLOBAL_LOCK = threading.Lock()   # engine construction / supervisor run


def maybe_serve_from_env() -> Optional[ObsHTTPServer]:
    """The opt-in seam the engine/supervisor call at construction/run:
    with ``PADDLE_TPU_OBS_HTTP_PORT`` set, start the process-global
    endpoint exactly once and hand it back; unset, return None at the
    cost of one env read. A bind failure (or unparsable port) logs ONCE
    and latches the opt-in off — a metrics port collision must never
    take serving down or spam a retry per engine."""
    global _GLOBAL, _DISABLED
    raw = os.environ.get("PADDLE_TPU_OBS_HTTP_PORT", "").strip()
    if not raw:
        return None
    with _GLOBAL_LOCK:
        if _GLOBAL is not None or _DISABLED:
            return _GLOBAL
        try:
            port = int(raw)
        except ValueError:
            _DISABLED = True
            _log.warning("obs http: PADDLE_TPU_OBS_HTTP_PORT=%r is not an "
                         "integer; scrape endpoint disabled", raw)
            return None
        try:
            _GLOBAL = ObsHTTPServer(port=port)
        except OSError as e:
            _DISABLED = True
            _log.warning("obs http: cannot bind port %s (%s); scrape "
                         "endpoint disabled", raw, e)
            return None
        _log.info("obs http: serving /metrics /healthz /debug on %s",
                  _GLOBAL.url)
        return _GLOBAL
