"""End-to-end tracing + crash flight recorder (ISSUE 12).

Three layers, one event envelope (``{"ts", "kind", "name", "attrs"}`` —
span events additionally carry ``trace``/``span``/``parent`` ids):

* **Spans** — ``with trace.span("serving.prefill", parent=ctx, rid=7):``
  opens one node of a span tree. Context propagates thread-locally (a
  nested ``span()`` on the same thread becomes a child automatically) and
  across threads explicitly: ``new_trace(label)`` mints a
  :class:`SpanContext` root that travels with the work item (the serving
  scheduler carries one per request, so a request's trace follows it from
  ``submit()`` on the caller thread through the engine step thread), and
  any ``span(..., parent=ctx)`` attaches to it. ``instant(...)`` records a
  point event into the same tree. Span begin/end pairing is structural —
  spans exist ONLY as context managers (enforced by the
  ``span-discipline`` lint rule), so every start has exactly one end on
  every exit path, including exceptions and simulated kills.
* **The trace buffer** — with ``PADDLE_TPU_TRACE=on`` every span/instant
  (plus per-op dispatch events via ``core.tensor._op_trace_hook``) lands
  in an in-process buffer; :func:`export_chrome` converts it to a Chrome
  trace-event JSON that loads in ``chrome://tracing`` / Perfetto (one
  track per trace, spans nested by time containment).
* **The flight recorder** — an ALWAYS-ON lock-free ring of the last N
  events (``PADDLE_TPU_FLIGHT_EVENTS``, default 512): lifecycle instants,
  injected/real fault events, watchdog trips, NaN skips, restores. On an
  abort path (``TrainAborted``, a watchdog trip, engine crash-recovery,
  an unhandled supervisor exit) :func:`flight_dump` snapshots the ring to
  a JSON file under ``PADDLE_TPU_TRACE_DIR`` — the post-mortem is on disk
  before the process is gone.

Overhead contract (the ``_op_metrics_hook`` discipline): with tracing off
(the default) ``span()`` is one global read returning a shared no-op
context manager, the per-op dispatch seam stays at its is-None probe, and
only explicit ``instant``/``record`` calls (request/step-rate lifecycle
sites, never per-op) pay one dict build + one ring slot write for the
always-on recorder. ``bench.py`` pins the captured-step p50 delta of
``off`` vs ``flight`` vs ``on`` in its ``trace_overhead`` block.

Health beacons ride along (``heartbeat(name)`` from the engine/supervisor
step loops and the watchdog poll threads); ``observability.http`` serves
them at ``/healthz`` next to ``/metrics`` and ``/debug/flight``.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional

__all__ = [
    "SpanContext", "FlightRecorder",
    "span", "instant", "record", "new_trace", "current",
    "mode", "enabled", "set_mode", "tracing",
    "events", "clear", "dropped", "make_event", "span_problems",
    "export_chrome", "trace_dir",
    "flight_recorder", "flight_dump",
    "heartbeat", "heartbeat_clear", "health", "beacon_detail",
]

_log = logging.getLogger(__name__)

_VALID_MODES = ("off", "on", "flight")

# soft cap on the "on"-mode buffer: tracing a runaway loop must degrade to
# dropped-event accounting, not an OOM
_BUFFER_CAP = 500_000
# cap on remembered track labels (export metadata only): a long-running
# engine mints one trace per request, and the label map must not become
# the leak the buffer cap exists to prevent
_TRACKS_CAP = 50_000

_DEFAULT_FLIGHT_EVENTS = 512
_DEFAULT_HEARTBEAT_TTL_S = 60.0


def _env_mode() -> str:
    raw = os.environ.get("PADDLE_TPU_TRACE", "").strip().lower()
    if raw in ("", "0", "false", "no", "off", "disable", "disabled"):
        return "off"
    if raw == "flight":
        return "flight"
    if raw in ("1", "true", "yes", "on"):
        return "on"
    # an unrecognized value must NOT silently enable the most expensive
    # tier (a typo of "flight" would otherwise install the per-op hook
    # and start buffering up to 500k events on a production hot path)
    _log.warning("PADDLE_TPU_TRACE=%r is not off|on|flight — tracing "
                 "stays OFF", raw)
    return "off"


_MODE = _env_mode()

_IDS = itertools.count(1)      # span + trace ids, one process-global space
_TLS = threading.local()


class SpanContext(NamedTuple):
    """Immutable handle for explicit cross-thread handoff: ``trace`` names
    the tree (one Chrome track), ``span`` the parent node (0 = root)."""

    trace: int
    span: int


class _TraceState:
    """The "on"-mode event buffer + track labels. Mutation is CPython-
    atomic (list.append / dict store), so the hot path takes no lock."""

    __slots__ = ("buffer", "tracks", "dropped")

    def __init__(self):
        self.buffer: List[Dict[str, Any]] = []
        self.tracks: Dict[int, str] = {}
        self.dropped = 0


_STATE = _TraceState()


def mode() -> str:
    return _MODE


def enabled() -> bool:
    """True when spans are being recorded (``on`` or ``flight``)."""
    return _MODE != "off"


def set_mode(m: str) -> None:
    """Switch tracing mode at runtime (``PADDLE_TPU_TRACE`` seeds the
    initial value at import). ``on`` also installs the per-op dispatch
    hook; ``off``/``flight`` keep the dispatch seam at its is-None
    probe."""
    global _MODE
    if m not in _VALID_MODES:
        raise ValueError(f"trace mode must be one of {_VALID_MODES}, "
                         f"got {m!r}")
    _MODE = m
    _sync_op_hook()


class tracing:
    """``with tracing("on"): ...`` — scoped mode switch for tests."""

    def __init__(self, m: str = "on"):
        self._mode = m
        self._prev = ""

    def __enter__(self):
        self._prev = _MODE
        set_mode(self._mode)
        return self

    def __exit__(self, *exc):
        set_mode(self._prev)


def make_event(kind: str, name: str, ts: Optional[float] = None,
               attrs: Optional[Dict[str, Any]] = None,
               **fields: Any) -> Dict[str, Any]:
    """The one event envelope every sink shares (the Chrome exporter, the
    flight recorder, and the JSONL step-telemetry stream): ``ts`` (a
    ``perf_counter`` instant), ``kind``, ``name``, ``attrs`` — plus
    optional span-tree fields (``trace``/``span``/``parent``)."""
    ev: Dict[str, Any] = {
        "ts": time.perf_counter() if ts is None else float(ts),
        "kind": kind, "name": name, "attrs": dict(attrs or {})}
    if fields:
        ev.update(fields)
    return ev


def _emit(ev: Dict[str, Any], ring: bool = True) -> None:
    if _MODE == "on":
        buf = _STATE.buffer
        if len(buf) < _BUFFER_CAP:
            buf.append(ev)
        else:
            _STATE.dropped += 1
    if ring:
        _FLIGHT.record(ev)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def _set_track(tid: int, label: str) -> None:
    """Remember a track label for the Chrome export. Labels only matter in
    "on" mode (the exporter reads them) and the map is capped — in
    "flight" mode a long-running engine mints one trace per request, and
    an unbounded label dict would be exactly the leak the buffer cap
    exists to prevent."""
    if _MODE == "on" and len(_STATE.tracks) < _TRACKS_CAP:
        _STATE.tracks.setdefault(tid, label)


def _stack() -> List[SpanContext]:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current() -> Optional[SpanContext]:
    """The innermost open span on THIS thread (for implicit parenting)."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


class _NoopSpan:
    """Shared disabled-mode span: one global read, nothing else."""

    __slots__ = ()
    ctx: Optional[SpanContext] = None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    """One live span. Only :func:`span` constructs these, and only as a
    context manager — begin/end pairing is structural, which is what lets
    the chaos suites assert every trace is balanced."""

    __slots__ = ("_name", "_attrs", "_parent", "ctx")

    def __init__(self, name: str, parent: Optional[SpanContext],
                 attrs: Dict[str, Any]):
        self._name = name
        self._attrs = attrs
        self._parent = parent
        self.ctx: Optional[SpanContext] = None

    def __enter__(self) -> "_Span":
        stack = _stack()
        if self._parent is not None:
            tr, par = self._parent.trace, self._parent.span
        elif stack:
            top = stack[-1]
            tr, par = top.trace, top.span
        else:
            tr, par = next(_IDS), 0
            _set_track(tr, self._name)
        sid = next(_IDS)
        self.ctx = SpanContext(tr, sid)
        stack.append(self.ctx)
        _emit({"ts": time.perf_counter(), "kind": "B", "name": self._name,
               "attrs": self._attrs, "trace": tr, "span": sid,
               "parent": par, "thread": threading.get_ident()})
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        stack = _stack()
        if stack and stack[-1] == self.ctx:
            stack.pop()
        elif self.ctx in stack:          # defensive: interleaved exit
            stack.remove(self.ctx)
        attrs = {"error": exc_type.__name__} if exc_type is not None else {}
        _emit({"ts": time.perf_counter(), "kind": "E", "name": self._name,
               "attrs": attrs, "trace": self.ctx.trace,
               "span": self.ctx.span})
        return False


def span(name: str, parent: Optional[SpanContext] = None, **attrs):
    """Open one span of the trace tree (context manager — the ONLY way to
    create a span). ``parent`` is an explicit :class:`SpanContext` for
    cross-thread handoff; omitted, the innermost open span on this thread
    (or a fresh root) parents it. Near-free when tracing is off."""
    if _MODE == "off":
        return _NOOP
    return _Span(name, parent, attrs)


def new_trace(label: str, **attrs) -> Optional[SpanContext]:
    """Mint a root context for a logical unit of work (one Chrome track):
    the cross-thread handle a serving request or training run carries.
    Returns None when tracing is off — every consumer treats the context
    as optional."""
    if _MODE == "off":
        return None
    tid = next(_IDS)
    _set_track(tid, label)
    _emit(make_event("ev", label, attrs=attrs, trace=tid, span=0, parent=0))
    return SpanContext(tid, 0)


def instant(name: str, parent: Optional[SpanContext] = None,
            **attrs) -> None:
    """A point event. Attached to ``parent`` (or the current span) in the
    trace tree when tracing is on; ALWAYS appended to the flight ring —
    instants are the coarse lifecycle/fault record the post-mortem needs,
    and they fire at request/step rate, never per op."""
    if parent is not None:
        tr, par = parent.trace, parent.span
    else:
        cur = current()
        tr, par = (cur.trace, cur.span) if cur is not None else (0, 0)
    _emit(make_event("i", name, attrs=attrs, trace=tr, parent=par))


def record(name: str, **attrs) -> None:
    """An un-parented lifecycle event (always in the flight ring; in the
    trace buffer too when tracing is on). The seam the fault injector and
    the watchdog use."""
    _emit(make_event("ev", name, attrs=attrs, trace=0, parent=0))


def events() -> List[Dict[str, Any]]:
    """Copy of the "on"-mode trace buffer."""
    return list(_STATE.buffer)


def dropped() -> int:
    return _STATE.dropped


def clear() -> None:
    """Reset the trace buffer + track names (test isolation seam; the
    flight ring has its own ``flight_recorder().clear()``)."""
    _STATE.buffer = []
    _STATE.tracks = {}
    _STATE.dropped = 0


def span_problems(evs: Optional[List[Dict[str, Any]]] = None) -> List[str]:
    """Structural validation the chaos suites assert on: every span begin
    has exactly one end (same id), no end without a begin, and every
    non-root parent id exists as a span in the same trace. Returns a list
    of human-readable problems ([] = the trace is a well-formed forest)."""
    evs = events() if evs is None else evs
    begins: Dict[int, Dict[str, Any]] = {}
    ended: Dict[int, int] = {}
    problems: List[str] = []
    for e in evs:
        if e["kind"] == "B":
            if e["span"] in begins:
                problems.append(f"span {e['span']} ({e['name']}) began twice")
            begins[e["span"]] = e
        elif e["kind"] == "E":
            if e["span"] not in begins:
                problems.append(f"span {e['span']} ({e['name']}) ended "
                                f"without a begin")
            ended[e["span"]] = ended.get(e["span"], 0) + 1
    for sid, b in begins.items():
        n = ended.get(sid, 0)
        if n != 1:
            problems.append(f"span {sid} ({b['name']}) has {n} ends")
        par = b.get("parent", 0)
        if par and par not in begins:
            # parent may be a new_trace root (span id 0 handled above) or
            # another span; a dangling nonzero parent is a broken handoff
            problems.append(f"span {sid} ({b['name']}) parent {par} is not "
                            f"a span in the buffer")
        elif par and begins[par].get("trace") != b.get("trace"):
            problems.append(f"span {sid} ({b['name']}) crosses traces "
                            f"{begins[par].get('trace')} -> {b.get('trace')}")
    return problems


# ---------------------------------------------------------------------------
# per-op dispatch hook ("on" mode only)
# ---------------------------------------------------------------------------

def _op_event_hook(op_name: str, t0: float, t1: float) -> None:
    """Installed into ``core.tensor._op_trace_hook`` while mode == "on":
    one complete event per eager dispatch, buffer-only (per-op noise must
    never churn the flight ring's post-mortem tail)."""
    cur = current()
    ev = {"ts": t0, "kind": "O", "name": op_name, "attrs": {},
          "dur": t1 - t0, "trace": cur.trace if cur is not None else 0}
    buf = _STATE.buffer
    if len(buf) < _BUFFER_CAP:
        buf.append(ev)
    else:
        _STATE.dropped += 1


def _sync_op_hook() -> None:
    """Install/remove the dispatch hook to match the mode. Deferred core
    import (observability is a foundation layer; ``paddle_tpu/__init__``
    re-syncs once the core is importable, covering an env-set mode)."""
    try:
        from ..core import tensor as _tensor_mod
    except ImportError:
        return  # why: early in package import the core does not exist yet;
        #        the package root calls _sync_op_hook() again at the end
    _tensor_mod._op_trace_hook = _op_event_hook if _MODE == "on" else None


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def trace_dir() -> str:
    """Where exports and flight dumps land: ``PADDLE_TPU_TRACE_DIR``, or a
    stable per-tmpdir default."""
    d = os.environ.get("PADDLE_TPU_TRACE_DIR", "").strip()
    return d or os.path.join(tempfile.gettempdir(), "paddle_tpu_obs")


def export_chrome(path: Optional[str] = None,
                  evs: Optional[List[Dict[str, Any]]] = None):
    """Convert the trace buffer to the Chrome trace-event format
    (``chrome://tracing`` / Perfetto-loadable). Spans become complete
    ("X") events on one track per trace id (nesting falls out of time
    containment), instants "i" events, per-op events "X" on their trace's
    track; a span left open by a crash exports as a bare "B" (Perfetto
    renders it to the end of the trace). Returns the document dict, or
    writes it to ``path`` and returns the path."""
    evs = events() if evs is None else list(evs)
    pid = os.getpid()
    base = min((e["ts"] for e in evs), default=0.0)

    def us(ts: float) -> float:
        return (ts - base) * 1e6

    out: List[Dict[str, Any]] = []
    open_b: Dict[int, Dict[str, Any]] = {}
    for e in evs:
        kind = e["kind"]
        tid = e.get("trace", 0)
        if kind == "B":
            open_b[e["span"]] = e
        elif kind == "E":
            b = open_b.pop(e.get("span", 0), None)
            if b is None:
                continue
            args = dict(b.get("attrs") or {})
            args.update(e.get("attrs") or {})
            args["span"] = b["span"]
            if b.get("parent"):
                args["parent"] = b["parent"]
            out.append({"name": b["name"], "cat": "paddle_tpu", "ph": "X",
                        "ts": us(b["ts"]), "dur": max(0.0, us(e["ts"]) -
                                                      us(b["ts"])),
                        "pid": pid, "tid": b.get("trace", 0), "args": args})
        elif kind == "O":
            out.append({"name": e["name"], "cat": "paddle_tpu.op",
                        "ph": "X", "ts": us(e["ts"]),
                        "dur": max(0.0, e.get("dur", 0.0) * 1e6),
                        "pid": pid, "tid": tid, "args": {}})
        else:   # "i" instants + "ev" lifecycle/step events
            out.append({"name": e["name"], "cat": "paddle_tpu",
                        "ph": "i", "s": "t" if tid else "g",
                        "ts": us(e["ts"]), "pid": pid, "tid": tid,
                        "args": dict(e.get("attrs") or {})})
    for b in open_b.values():   # crash-open spans: begin-only is loadable
        out.append({"name": b["name"], "cat": "paddle_tpu", "ph": "B",
                    "ts": us(b["ts"]), "pid": pid,
                    "tid": b.get("trace", 0),
                    "args": dict(b.get("attrs") or {})})
    out.append({"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": f"paddle_tpu[{pid}]"}})
    for tid, label in list(_STATE.tracks.items()):
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": label}})
    doc = {"traceEvents": out, "displayTimeUnit": "ms"}
    if path is None:
        return doc
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, default=str)
    return path


def maybe_export_chrome(label: str) -> Optional[str]:
    """Operator-facing auto-export: when tracing is fully on AND the
    operator pointed ``PADDLE_TPU_TRACE_DIR`` somewhere, drop a Chrome
    trace there (the engine/supervisor call this at shutdown). Never
    raises; returns the path or None."""
    if _MODE != "on" or not os.environ.get("PADDLE_TPU_TRACE_DIR",
                                           "").strip():
        return None
    path = os.path.join(trace_dir(), f"trace-{label}-{os.getpid()}.json")
    try:
        return export_chrome(path)
    except OSError as e:
        _log.error("trace: chrome export to %s failed: %s", path, e)
        return None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Lock-free ring of the last N envelope events.

    Writers pay one C-level counter bump (``itertools.count``) and one
    list-slot store — no lock, safe from any thread including the
    watchdog's. ``snapshot()`` reorders by sequence number; a dump taken
    while writers race may miss the very newest slot, which is the right
    trade for a recorder that must never stall the path it observes.
    """

    __slots__ = ("capacity", "_slots", "_seq")

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            raw = os.environ.get("PADDLE_TPU_FLIGHT_EVENTS", "").strip()
            try:
                capacity = int(raw) if raw else _DEFAULT_FLIGHT_EVENTS
            except ValueError:
                capacity = _DEFAULT_FLIGHT_EVENTS
        self.capacity = max(8, int(capacity))
        self._slots: List[Optional[Any]] = [None] * self.capacity
        self._seq = itertools.count()

    def record(self, ev: Dict[str, Any]) -> None:
        i = next(self._seq)
        self._slots[i % self.capacity] = (i, ev)

    def snapshot(self) -> List[Dict[str, Any]]:
        entries = [s for s in list(self._slots) if s is not None]
        entries.sort(key=lambda p: p[0])
        return [ev for _, ev in entries]

    def clear(self) -> None:
        self._slots = [None] * self.capacity

    def dump(self, reason: str, path: Optional[str] = None,
             **info: Any) -> Optional[str]:
        """Write the ring's last-N snapshot to a JSON file (atomic
        replace; one file per (pid, reason) so repeated aborts keep the
        LATEST post-mortem). Never raises — a failing dump must not turn
        an abort into a second crash. Returns the path or None."""
        evs = self.snapshot()
        doc = {"schema": 1, "reason": reason, "pid": os.getpid(),
               "dumped_at": time.time(),
               "dumped_perf_ts": time.perf_counter(),
               "info": dict(info), "events": evs}
        try:
            # ISSUE 16: the post-mortem names the programs that were live
            # AND what they should have cost (records + HBM ledger);
            # flight_snapshot itself never raises, the guard covers import
            from . import cost as _cost
            doc["cost"] = _cost.flight_snapshot()
        except Exception:
            doc["cost"] = None
        if path is None:
            slug = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in reason)
            path = os.path.join(trace_dir(),
                                f"flight-{os.getpid()}-{slug}.json")
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            _log.error("flight recorder: dump to %s failed: %s", path, e)
            return None
        from . import inc as _inc   # deferred: trace is imported by the
        _inc("trace.flight_dumps_total", reason=reason)  # package __init__
        _log.warning("flight recorder: %d events -> %s (reason=%s)",
                     len(evs), path, reason)
        return path


_FLIGHT = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _FLIGHT


def flight_dump(reason: str, **info: Any) -> Optional[str]:
    """Dump the process-global flight ring (see
    :meth:`FlightRecorder.dump`)."""
    return _FLIGHT.dump(reason, **info)


# ---------------------------------------------------------------------------
# health beacons (the /healthz surface)
# ---------------------------------------------------------------------------

class _Heartbeats:
    __slots__ = ("beats",)

    def __init__(self):
        self.beats: Dict[str, Dict[str, Any]] = {}


_HEALTH = _Heartbeats()


def heartbeat(name: str, ttl_s: float = _DEFAULT_HEARTBEAT_TTL_S,
              ok: bool = True) -> None:
    """Liveness beacon: the engine/supervisor step loops (and the watchdog
    poll threads) ping one per iteration; ``/healthz`` reports a component
    unhealthy once its beacon goes stale past ``ttl_s`` (a loop thread
    wedged inside a compiled call stops beating — exactly the failure an
    external prober needs to see) or it last reported ``ok=False``."""
    _HEALTH.beats[name] = {"at": time.monotonic(), "ttl_s": float(ttl_s),
                           "ok": bool(ok)}


def heartbeat_clear(name: str) -> None:
    """Retire a beacon (clean shutdown is not a liveness failure)."""
    _HEALTH.beats.pop(name, None)


def health() -> Dict[str, Any]:
    """The /healthz document: per-component age vs ttl; overall ``ok``
    only when every registered beacon is fresh and ok.

    Each component carries the full per-beacon detail (ISSUE 15 — the
    router and the front door route on it, a multi-replica process
    registers one ``serving.engine.<replica>`` beacon per engine):
    ``age_s`` since the last beat, the beacon's ``ttl_s``, an explicit
    ``stale`` bit (age past ttl — a loop thread wedged in a compiled call
    stops beating), and ``ok`` (fresh AND the last beat reported
    healthy) — not just one process-global staleness bit."""
    now = time.monotonic()
    comps: Dict[str, Any] = {}
    healthy = True
    # copy first: heartbeat() inserts new keys lock-free from other
    # threads (an engine's first beat racing a scrape), and iterating the
    # live dict would raise mid-/healthz
    for name, b in sorted(dict(_HEALTH.beats).items()):
        comps[name] = c = _beacon_component(b, now)
        healthy = healthy and c["ok"]
    # ISSUE 16: HBM ledger detail rides along 503-INDEPENDENTLY — low
    # headroom warns (once, in the cost module) but never flips the
    # routing status; the component's ok is always True by contract
    try:
        from . import cost as _cost
        hbm = _cost.healthz_component()
        if hbm is not None:
            comps["hbm"] = hbm
    except Exception:
        # why silent: the hbm component is advisory detail — a ledger
        # walk failing mid-scrape must not turn /healthz into a 500,
        # and the failure is already counted by the cost module
        _log.debug("healthz: hbm component unavailable", exc_info=True)
    return {"status": "ok" if healthy else "unhealthy",
            "components": comps, "pid": os.getpid()}


def _beacon_component(b: Dict[str, Any], now: float) -> Dict[str, Any]:
    """One beacon's component document — the single definition of the
    stale/ok semantics both :func:`health` and :func:`beacon_detail`
    report (they must never drift: the router's rotation signal IS the
    /healthz document)."""
    age = now - b["at"]
    stale = age > b["ttl_s"]
    return {"age_s": round(age, 3), "ttl_s": b["ttl_s"], "stale": stale,
            "ok": b["ok"] and not stale}


def beacon_detail(name: str) -> Optional[Dict[str, Any]]:
    """One beacon's /healthz component (or None when it never beat):
    the router's per-replica liveness probe — a replica whose engine
    beacon is ``stale`` leaves the rotation without an HTTP scrape."""
    b = dict(_HEALTH.beats).get(name)
    if b is None:
        return None
    return _beacon_component(b, time.monotonic())


_sync_op_hook()
