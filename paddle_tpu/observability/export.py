"""Exporters: Prometheus text exposition + JSONL step telemetry.

Two consumers, two formats:

* ``prometheus_text`` — the pull/scrape surface: one text document of every
  family in the registry, Prometheus exposition format (``# TYPE`` headers,
  ``_total``-as-written names with dots mapped to underscores, cumulative
  ``_bucket{le=...}`` histogram lines). ``parse_prometheus_text`` is the
  inverse used by tests to prove the round trip.
* ``StepTelemetryWriter`` — the push/stream surface: one JSON record per
  training step with counter DELTAS since the previous step (plus absolute
  gauges), the stream the hapi ``StepTelemetry`` callback writes. Since
  ISSUE 12 each record is the shared trace event envelope
  (``trace.make_event``: ``ts``/``kind``/``name``/``attrs`` — kind
  ``"step"``, the step number + counters + gauges inside ``attrs``) and is
  mirrored into the flight recorder, so a crash dump's tail carries the
  last steps' telemetry next to the fault events. ``bench.py``'s
  ``detail.telemetry`` block reads the registry snapshot directly and is
  byte-identical to before.
"""

from __future__ import annotations

import json
from typing import Any, Dict, IO, List, Optional, Union

from . import trace as _trace
from .registry import Counter, Gauge, Histogram, Registry

__all__ = ["prometheus_text", "parse_prometheus_text",
           "StepTelemetryWriter", "read_jsonl"]


def _prom_name(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


def _escape_label_value(v: str) -> str:
    # exposition format: label values must escape \, " and newline
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _prom_labels(labelnames, key, extra: str = "") -> str:
    parts = [f'{_prom_name(n)}="{_escape_label_value(v)}"'
             for n, v in zip(labelnames, key)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"  # exposition format spells non-finite values out
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


def prometheus_text(registry: Registry) -> str:
    lines: List[str] = []
    for m in registry.families():
        pname = _prom_name(m.name)
        series = m.series()
        if not series:
            continue
        if m.help:
            lines.append(f"# HELP {pname} {m.help}")
        lines.append(f"# TYPE {pname} {m.kind}")
        if isinstance(m, Histogram):
            for key, st in sorted(series.items()):
                for bound, c in zip(m.boundaries, st["buckets"]):
                    le = 'le="%r"' % (bound,)
                    labels = _prom_labels(m.labelnames, key, le)
                    lines.append(f"{pname}_bucket{labels} {c}")
                labels = _prom_labels(m.labelnames, key, 'le="+Inf"')
                lines.append(f"{pname}_bucket{labels} {st['buckets'][-1]}")
                lines.append(f"{pname}_sum{_prom_labels(m.labelnames, key)}"
                             f" {_fmt(st['sum'])}")
                lines.append(f"{pname}_count{_prom_labels(m.labelnames, key)}"
                             f" {st['count']}")
        else:
            for key, val in sorted(series.items()):
                lines.append(f"{pname}{_prom_labels(m.labelnames, key)}"
                             f" {_fmt(val)}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Inverse of ``prometheus_text`` for round-trip tests.

    Returns ``{sample_name: {label_string: value}}`` where ``label_string``
    is the raw ``{...}`` section ("" when unlabeled). Histogram samples
    appear under their ``_bucket``/``_sum``/``_count`` expansions, exactly
    as a scraper sees them.
    """
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels = "{" + rest
        else:
            name, labels = name_part, ""
        out.setdefault(name, {})[labels] = float(value_part)
    return out


def _flat_counters(registry: Registry) -> Dict[str, float]:
    """Counters (and histogram counts) as a flat {sample_name: value} map —
    the delta basis for step telemetry."""
    flat: Dict[str, float] = {}
    for m in registry.families():
        series = m.series()
        for key, val in series.items():
            suffix = "" if not key else \
                "{" + ",".join(f"{n}={v}"
                               for n, v in zip(m.labelnames, key)) + "}"
            if isinstance(m, Counter):
                flat[m.name + suffix] = float(val)
            elif isinstance(m, Histogram):
                flat[m.name + suffix + ".count"] = float(val["count"])
                flat[m.name + suffix + ".sum"] = float(val["sum"])
    return flat


def _flat_gauges(registry: Registry) -> Dict[str, float]:
    flat: Dict[str, float] = {}
    for m in registry.families():
        if not isinstance(m, Gauge):
            continue
        for key, val in m.series().items():
            suffix = "" if not key else \
                "{" + ",".join(f"{n}={v}"
                               for n, v in zip(m.labelnames, key)) + "}"
            flat[m.name + suffix] = float(val)
    return flat


class StepTelemetryWriter:
    """JSONL sink: one envelope event per training step.

    Record shape (the ISSUE 12 trace envelope)::

        {"ts": perf_counter_s, "kind": "step", "name": "telemetry",
         "attrs": {"step": N,
                   "counters": {name: delta_since_last_record},
                   "gauges": {name: value}, ...extra}}

    Counter deltas (not absolutes) are recorded so a consumer can plot
    per-step rates without diffing, and so concatenated runs don't need a
    monotonic epoch. The first record's deltas are measured from writer
    construction (``baseline="now"``, default) or from zero
    (``baseline="zero"``). Every record is also appended to the flight
    recorder ring, so a post-mortem dump ends with the last steps'
    telemetry.
    """

    def __init__(self, path_or_file: Union[str, IO[str]],
                 registry: Optional[Registry] = None,
                 baseline: str = "now"):
        from . import default_registry
        self._registry = registry if registry is not None else \
            default_registry()
        if isinstance(path_or_file, str):
            self._fh: IO[str] = open(path_or_file, "a")
            self._owns = True
        else:
            self._fh = path_or_file
            self._owns = False
        self._prev = _flat_counters(self._registry) \
            if baseline == "now" else {}

    def write(self, step: int, **extra: Any) -> Dict[str, Any]:
        cur = _flat_counters(self._registry)
        deltas = {k: v - self._prev.get(k, 0.0)
                  for k, v in cur.items()
                  if v != self._prev.get(k, 0.0)}
        self._prev = cur
        attrs: Dict[str, Any] = {"step": int(step), "counters": deltas,
                                 "gauges": _flat_gauges(self._registry)}
        attrs.update(extra)
        rec = _trace.make_event("step", "telemetry", attrs=attrs)
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        _trace.flight_recorder().record(rec)
        return rec

    def close(self) -> None:
        if self._owns:
            self._fh.close()

    def __enter__(self) -> "StepTelemetryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
