"""``paddle.hub``: load models from a hubconf.py (reference:
python/paddle/hapi/hub.py). Zero-egress build: ``source='local'`` only —
github/gitee sources raise with guidance."""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str) -> None:
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access; this build is "
            "zero-egress. Clone the repo locally and use source='local' "
            "with repo_dir pointing at it.")


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:  # noqa: A001 (paddle API name)
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise RuntimeError(f"model {model!r} not found in {repo_dir!r}")
    return fn.__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"model {model!r} not found in {repo_dir!r}")
    return fn(**kwargs)
