"""``paddle.signal``: stft / istft (reference: python/paddle/signal.py —
frame+window+FFT forward, overlap-add inverse with window-envelope
normalization)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .core.tensor import Tensor, apply
from .ops._helpers import ensure_tensor

__all__ = ["stft", "istft"]


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window: Optional[Tensor] = None,
         center: bool = True, pad_mode: str = "reflect",
         normalized: bool = False, onesided: bool = True, name=None) -> Tensor:
    """(B?, T) real → (B?, F, frames) complex spectrogram."""
    x = ensure_tensor(x)
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if window is not None:
        wdata = ensure_tensor(window)._data
        if wl < n_fft:
            lpad = (n_fft - wl) // 2
            wdata = jnp.pad(wdata, (lpad, n_fft - wl - lpad))
    else:
        wdata = jnp.ones((n_fft,), jnp.float32)

    def f(arr):
        squeeze = arr.ndim == 1
        if squeeze:
            arr = arr[None]
        if center:
            pad = n_fft // 2
            mode = "reflect" if pad_mode == "reflect" else "constant"
            arr = jnp.pad(arr, [(0, 0)] * (arr.ndim - 1) + [(pad, pad)],
                          mode=mode)
        t = arr.shape[-1]
        n_frames = 1 + (t - n_fft) // hop
        idx = (jnp.arange(n_frames)[:, None] * hop +
               jnp.arange(n_fft)[None, :])
        frames = arr[..., idx] * wdata
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -1, -2)  # (..., F, frames)
        return out[0] if squeeze else out

    return apply("stft", f, x)


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window: Optional[Tensor] = None,
          center: bool = True, normalized: bool = False,
          onesided: bool = True, length: Optional[int] = None,
          return_complex: bool = False, name=None) -> Tensor:
    """Inverse STFT via overlap-add with squared-window normalization."""
    x = ensure_tensor(x)
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    if window is not None:
        wdata = ensure_tensor(window)._data
        if wl < n_fft:
            lpad = (n_fft - wl) // 2
            wdata = jnp.pad(wdata, (lpad, n_fft - wl - lpad))
    else:
        wdata = jnp.ones((n_fft,), jnp.float32)

    def f(spec):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        spec = jnp.swapaxes(spec, -1, -2)  # (..., frames, F)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * wdata
        n_frames = frames.shape[-2]
        t = n_fft + (n_frames - 1) * hop
        lead = frames.shape[:-2]
        sig = jnp.zeros(lead + (t,), frames.dtype)
        env = jnp.zeros((t,), jnp.float32)
        idx = (jnp.arange(n_frames)[:, None] * hop +
               jnp.arange(n_fft)[None, :])
        sig = sig.at[..., idx.reshape(-1)].add(
            frames.reshape(lead + (-1,)))
        env = env.at[idx.reshape(-1)].add(
            jnp.tile(wdata * wdata, n_frames))
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            sig = sig[..., n_fft // 2: t - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig[0] if squeeze else sig

    return apply("istft", f, x)
