"""paddle.sparse.nn.functional parity surface.

Reference: python/paddle/sparse/nn/functional/ (activation.py, conv.py,
pooling.py, transformer.py). TPU-native lowering mirrors the layer
classes: scatter-to-dense -> XLA conv/reduce_window -> re-sparsify for
full convs/pooling, gather-at-sites for submanifold convs, and
segment-softmax SDDMM/SpMM for the CSR-masked attention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from . import SparseCooTensor, SparseCsrTensor, _unary

__all__ = ["relu", "relu6", "leaky_relu", "softmax", "conv3d", "subm_conv3d",
           "conv2d", "subm_conv2d", "max_pool3d", "attention"]


def relu(x, name=None):
    return _unary("relu", lambda v: jnp.maximum(v, 0.0))(x)


def relu6(x, name=None):
    return _unary("relu6", lambda v: jnp.clip(v, 0.0, 6.0))(x)


def leaky_relu(x, negative_slope: float = 0.01, name=None):
    return _unary("leaky_relu",
                  lambda v: jnp.where(v >= 0, v, negative_slope * v))(x)


def softmax(x, axis: int = -1, name=None):
    from .nn import Softmax
    return Softmax(axis)(x)


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd, subm,
             data_format):
    """Shared functional sparse conv (see nn._SparseConvND for the layout
    contract: COO indices over [N, *spatial], dense channel values;
    weight [*k, C/groups, M])."""
    fmt = "NDHWC" if nd == 3 else "NHWC"
    if data_format not in (None, fmt):
        raise ValueError(f"sparse conv{nd}d expects {fmt}")
    dimnums = (fmt, ("DHWIO" if nd == 3 else "HWIO"), fmt)
    stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    dilation = (dilation,) * nd if isinstance(dilation, int) \
        else tuple(dilation)
    if subm:
        # submanifold gathers the output at INPUT coordinates, so the conv
        # must be size-preserving; silently accepting other configs would
        # gather clamped/shifted edge values (jax clamps OOB indices)
        if stride != (1,) * nd:
            raise ValueError("submanifold sparse conv requires stride 1")
        w_k = tuple(int(s) for s in np.shape(
            weight._data if isinstance(weight, Tensor) else weight)[:nd])
        for p, d, kk in zip(padding, dilation, w_k):
            if 2 * p != d * (kk - 1):
                raise ValueError(
                    "submanifold sparse conv requires size-preserving "
                    "padding (2*padding == dilation*(kernel-1)); got "
                    f"padding={padding}, dilation={dilation}, kernel={w_k}")
    if x.sparse_dim != nd + 1 or x.dense_dim != 1:
        raise ValueError(
            f"sparse conv{nd}d expects COO with indices over [N, *spatial] "
            "and dense channel values")
    weight = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    if bias is not None and not isinstance(bias, Tensor):
        bias = Tensor(jnp.asarray(bias))
    out_channels = int(weight._data.shape[-1])
    idx = x._indices
    shape = x._shape

    def fn(v, w, *maybe_b):
        dense = jnp.zeros(shape, v.dtype).at[tuple(idx)].add(v)
        out = jax.lax.conv_general_dilated(
            dense, w, window_strides=stride,
            padding=[(p, p) for p in padding],
            rhs_dilation=dilation, dimension_numbers=dimnums,
            feature_group_count=groups)
        if subm:
            out = out[tuple(idx)]
            if maybe_b:
                out = out + maybe_b[0]
        return out

    args = [x._values, weight] + ([bias] if (bias is not None and subm) else [])
    out = apply(f"{'subm_' if subm else ''}sparse_conv{nd}d_fn", fn, *args)
    if subm:
        return SparseCooTensor(idx, out, shape[:nd + 1] + (out_channels,),
                               x._coalesced)
    from .nn import _dense_to_coo
    return _dense_to_coo(out, bias)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    False, data_format)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    True, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    False, data_format)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    True, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse max pooling over ACTIVE sites only (reference
    python/paddle/sparse/nn/functional/pooling.py): an output site is
    active iff its window contains an active input; inactive positions
    never contribute (lowered with a -inf background). ``ceil_mode``
    extends hi-side padding so the trailing partial window emits. Output
    nnz is data-dependent, so this runs eagerly (MIGRATING.md #2)."""
    nd = 3
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d expects NDHWC")
    if x.sparse_dim != nd + 1 or x.dense_dim != 1:
        raise ValueError("sparse max_pool3d expects COO [N, D, H, W] + "
                         "channel values")
    k = (kernel_size,) * nd if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    s = k if stride is None else (
        (stride,) * nd if isinstance(stride, int) else tuple(stride))
    p = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    idx = np.asarray(x._indices)
    shape = x._shape
    vals = np.asarray(x._values._data, np.float32)
    dense = np.full(shape, -np.inf, np.float32)
    dense[tuple(idx)] = vals
    window = (1,) + k + (1,)
    strides = (1,) + s + (1,)
    pad_cfg = [(0, 0)] + [(pp, pp) for pp in p] + [(0, 0)]
    if ceil_mode:
        # emit the trailing partial window (reference ceil rule: the extra
        # window must still START inside input+pad_lo) — extend hi padding;
        # -inf background keeps the extension out of every max
        for j in range(nd):
            length = shape[1 + j]
            eff = length + 2 * p[j] - k[j]
            if eff % s[j] != 0:
                out_ceil = -(-eff // s[j]) + 1
                if (out_ceil - 1) * s[j] >= length + p[j]:
                    continue
                hi_extra = (out_ceil - 1) * s[j] + k[j] - (length + 2 * p[j])
                lo, hi = pad_cfg[1 + j]
                pad_cfg[1 + j] = (lo, hi + hi_extra)
    pooled = jax.lax.reduce_window(jnp.asarray(dense), -jnp.inf, jax.lax.max,
                                   window, strides, pad_cfg)
    pooled = np.asarray(pooled)
    active = np.isfinite(pooled).any(axis=-1)
    out_idx = np.stack(np.nonzero(active))
    out_vals = pooled[tuple(out_idx)]
    out_vals[~np.isfinite(out_vals)] = 0.0  # channels with no active input
    return SparseCooTensor(out_idx, out_vals,
                           tuple(active.shape) + (shape[-1],), True)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """CSR-masked scaled-dot-product attention (reference
    python/paddle/sparse/nn/functional/transformer.py attention):
    q/k/v (B, H, L, D) dense; ``sparse_mask`` a 2-D SparseCsrTensor
    (L, L) giving the attention LAYOUT shared by every batch*head (the
    reference takes (B*H, L, L); pass the shared per-head pattern here —
    the TPU lowering keeps one static pattern for the whole batch).
    Scores are computed ONLY at stored positions (SDDMM), softmaxed per
    row over stored entries, then SpMM'd with V. key_padding_mask (B, L)
    and attn_mask (L, L) follow the reference: masked positions drop out
    of the normalization (additive -inf)."""
    if not isinstance(sparse_mask, SparseCsrTensor):
        raise ValueError("sparse_mask must be a 2-D SparseCsrTensor")
    q = query if isinstance(query, Tensor) else Tensor(jnp.asarray(query))
    k = key if isinstance(key, Tensor) else Tensor(jnp.asarray(key))
    v = value if isinstance(value, Tensor) else Tensor(jnp.asarray(value))
    b, h, L, d = q._data.shape
    rows = sparse_mask._rows()
    cols = sparse_mask._cols
    m = sparse_mask._shape[0]
    kp = None if key_padding_mask is None else (
        key_padding_mask._data if isinstance(key_padding_mask, Tensor)
        else jnp.asarray(key_padding_mask))
    am = None if attn_mask is None else (
        attn_mask._data if isinstance(attn_mask, Tensor)
        else jnp.asarray(attn_mask))

    def fn(qa, ka, va):
        qf = qa.reshape(b * h, L, d).astype(jnp.float32)
        kf = ka.reshape(b * h, L, d).astype(jnp.float32)
        vf = va.reshape(b * h, L, d).astype(jnp.float32)
        scale = 1.0 / np.sqrt(d)

        def one(args):
            qi, ki, vi, bi = args
            s = jnp.sum(qi[rows] * ki[cols], axis=-1) * scale  # SDDMM
            if am is not None:
                s = s + am[rows, cols]
            if kp is not None:
                s = s + kp[bi][cols]
            smax = jax.ops.segment_max(s, rows, num_segments=m)
            e = jnp.exp(s - smax[rows])
            denom = jax.ops.segment_sum(e, rows, num_segments=m)
            p = e / jnp.maximum(denom[rows], 1e-30)
            out = jax.ops.segment_sum(p[:, None] * vi[cols], rows,
                                      num_segments=m)  # SpMM
            return out

        bh_batch = jnp.repeat(jnp.arange(b), h)
        # vmap, not lax.map: all B*H heads share one pattern — run them as
        # one batched SDDMM/softmax/SpMM program instead of a serial scan
        out = jax.vmap(one)((qf, kf, vf, bh_batch))
        return out.reshape(b, h, L, d).astype(qa.dtype)

    args = [q, k, v]
    return apply("sparse_attention", fn, *args)
