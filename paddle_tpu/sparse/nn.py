"""``paddle.sparse.nn``: layers over sparse tensors.

Parity surface: python/paddle/sparse/nn/ (ReLU, Softmax, Conv3D, SubmConv3D,
BatchNorm — no line cites: reference mount was empty, see SURVEY.md
provenance). TPU-native note: XLA has no sparse conv kernels (the reference
uses gather-scatter CUDA rulebooks); Conv3D/SubmConv3D lower to a dense
``lax.conv_general_dilated`` over the densified input — bit-identical
semantics, efficient on MXU for the moderate resolutions TPUs favor, and the
submanifold variant re-masks the output to the input's active sites. The
active-site set (nnz) stays static under jit.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..nn.layer import Layer
from ..nn.initializer import XavierUniform
from . import SparseCooTensor, relu as _relu_fn

__all__ = ["ReLU", "Softmax", "Conv3D", "SubmConv3D", "BatchNorm"]


class ReLU(Layer):
    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        return _relu_fn(x)


class Softmax(Layer):
    """Row-wise softmax over the last sparse axis (parity:
    paddle.sparse.nn.Softmax for 2-D COO/CSR): normalization runs per-row
    over the *stored* entries via segment ops."""

    def __init__(self, axis: int = -1):
        super().__init__()
        if axis != -1:
            raise NotImplementedError("sparse softmax supports axis=-1")

    def forward(self, x):
        from . import SparseCsrTensor, coalesce
        is_csr = hasattr(x, "crows") and x.is_sparse_csr()
        coo = x.to_sparse_coo() if is_csr else coalesce(x)
        if len(coo._shape) != 2 or coo.dense_dim != 0:
            raise NotImplementedError("sparse softmax supports 2-D tensors")
        rows = coo._indices[0]
        m = coo._shape[0]

        def fn(v):
            row_max = jax.ops.segment_max(v, rows, num_segments=m)
            e = jnp.exp(v - row_max[rows])
            denom = jax.ops.segment_sum(e, rows, num_segments=m)
            return e / denom[rows]

        vals = apply("sparse_softmax", fn, coo._values)
        if is_csr:
            return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
        return SparseCooTensor(coo._indices, vals, coo._shape, True)


class _SparseConvND(Layer):
    """Shared impl for the 2-D/3-D sparse convs on channels-last COO
    inputs (indices over [N, *spatial], dense channel values). Lowered as
    scatter-to-dense -> XLA conv -> re-sparsify (or gather at the input
    sites for submanifold convs)."""

    SUBM = False
    NDIM = 3  # spatial rank
    DATA_FORMAT = "NDHWC"
    DIMNUMS = ("NDHWC", "DHWIO", "NDHWC")

    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size=3, stride=1, padding=0, dilation=1, groups=1,
                 padding_mode: str = "zeros", weight_attr=None,
                 bias_attr=None, data_format: str = None):
        super().__init__()
        nd = self.NDIM
        if data_format not in (None, self.DATA_FORMAT):
            raise ValueError(f"sparse conv expects {self.DATA_FORMAT}")
        k = ((kernel_size,) * nd if isinstance(kernel_size, int)
             else tuple(kernel_size))
        self.kernel_size = k
        self.stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
        self.padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)
        self.dilation = (dilation,) * nd if isinstance(dilation, int) else tuple(dilation)
        self.groups = groups
        self.in_channels = in_channels
        self.out_channels = out_channels
        if self.SUBM:
            if self.stride != (1,) * nd:
                raise ValueError("submanifold sparse conv requires stride 1")
            # submanifold gathers output at *input* coordinates, so the conv
            # must preserve spatial dims: 2p == dilation*(k-1) per axis
            for p, d, kk in zip(self.padding, self.dilation, k):
                if 2 * p != d * (kk - 1):
                    raise ValueError(
                        "submanifold sparse conv requires size-preserving "
                        "padding (2*padding == dilation*(kernel-1)); got "
                        f"padding={self.padding}, dilation={self.dilation}, "
                        f"kernel={k}")
        # reference kernel layout: [kd, kh, kw, in/groups, out]
        self.weight = self.create_parameter(
            (*k, in_channels // groups, out_channels),
            attr=weight_attr, default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        # one lowering, two surfaces: the functional op is the
        # implementation (scatter-to-dense -> XLA conv -> gather at input
        # sites for subm / re-sparsify otherwise)
        from .functional import _conv_nd
        return _conv_nd(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.NDIM, self.SUBM,
                        self.DATA_FORMAT)


_SparseConv3D = _SparseConvND  # back-compat alias


def _dense_to_coo(x: Tensor, bias: Optional[Tensor] = None) -> SparseCooTensor:
    """Eager re-sparsification of a dense NDHWC tensor (sites with any
    non-zero channel); ``bias`` is added after site selection so it lands
    only on retained sites."""
    import numpy as np
    arr = np.asarray(x._data)
    mask = np.any(arr != 0, axis=-1)
    sites = np.stack(np.nonzero(mask))  # [4, nnz]
    idx_t = tuple(jnp.asarray(sites))

    if bias is not None:
        vals = apply("sparse_gather_sites", lambda d, b: d[idx_t] + b, x, bias)
    else:
        vals = apply("sparse_gather_sites", lambda d: d[idx_t], x)
    return SparseCooTensor(sites, vals, x.shape, coalesced=True)


class Conv3D(_SparseConv3D):
    SUBM = False


class SubmConv3D(_SparseConv3D):
    SUBM = True


class BatchNorm(Layer):
    """BatchNorm over the channel (last, dense) axis of a COO tensor —
    statistics are computed over stored values only, matching the reference's
    sparse BN semantics."""

    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, weight_attr=None, bias_attr=None,
                 data_format: str = "NDHWC", use_global_stats=None,
                 name=None):
        super().__init__()
        from ..nn.initializer import Constant
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        self._mean = self.create_buffer("_mean",
                                        jnp.zeros((num_features,)))
        self._variance = self.create_buffer("_variance",
                                            jnp.ones((num_features,)))

    def create_buffer(self, name, value):
        t = Tensor(value)
        self.register_buffer(name.lstrip("_"), t)
        return t

    def forward(self, x: SparseCooTensor) -> SparseCooTensor:
        eps = self.epsilon
        mom = self.momentum
        training = self.training

        if training:
            def fn(v, w, b):
                mean = v.mean(axis=0)
                var = v.var(axis=0)
                y = (v - mean) / jnp.sqrt(var + eps) * w + b
                return y, mean, var

            vals, mean, var = apply("sparse_batch_norm", fn, x._values,
                                    self.weight, self.bias)
            self._mean._set_data(mom * self._mean._data +
                                 (1 - mom) * mean._data)
            self._variance._set_data(mom * self._variance._data +
                                     (1 - mom) * var._data)
        else:
            rm, rv = self._mean, self._variance

            def fn(v, w, b, m, s):
                return (v - m) / jnp.sqrt(s + eps) * w + b

            vals = apply("sparse_batch_norm_infer", fn, x._values,
                         self.weight, self.bias, rm, rv)
        return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)


# ---------------------------------------------------------------------------
# round-3 surface wave: activations + 2-D sparse convs
# (upstream python/paddle/sparse/nn/)
# ---------------------------------------------------------------------------

class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01):
        super().__init__()
        self.negative_slope = float(negative_slope)

    def forward(self, x):
        from . import _unary
        return _unary("leaky_relu",
                      lambda v: jnp.where(v >= 0, v,
                                          self.negative_slope * v))(x)


class ReLU6(Layer):
    def forward(self, x):
        from . import _unary
        return _unary("relu6", lambda v: jnp.clip(v, 0.0, 6.0))(x)


class _SparseConv2D(_SparseConvND):
    NDIM = 2
    DATA_FORMAT = "NHWC"
    DIMNUMS = ("NHWC", "HWIO", "NHWC")


class Conv2D(_SparseConv2D):
    SUBM = False


class SubmConv2D(_SparseConv2D):
    SUBM = True


__all__ += ["LeakyReLU", "ReLU6", "Conv2D", "SubmConv2D"]


class MaxPool3D(Layer):
    """Parity: paddle.sparse.nn.MaxPool3D (active-site max pooling)."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        from . import functional as F
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


# paddle.sparse.nn.functional lives beside the layers (upstream package
# layout); imported last — it reuses the layer internals above
from . import functional  # noqa: E402,F401

__all__ += ["MaxPool3D", "functional"]
