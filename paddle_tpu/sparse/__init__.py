"""``paddle.sparse``: COO/CSR sparse tensors and ops.

Parity surface: python/paddle/sparse/ + paddle/phi/kernels/sparse/ (upstream
``SparseCooTensor``/``SparseCsrTensor`` core types and the sparse op set —
no line cites: reference mount was empty, see SURVEY.md provenance).

TPU-native design: sparsity is a *format*, not a kernel library — XLA has no
native sparse types, so COO/CSR are index+values pairs whose compute lowers
to dense gathers/segment-sums (MXU/VPU-friendly, static shapes once nnz is
fixed at construction). The values leaf is a framework ``Tensor``, so every
sparse op that touches values flows through the op-dispatch layer and is
autograd-capable (gradients w.r.t. values; indices are structural).
``nnz`` is a trace-time constant — under ``jit`` the sparsity pattern is
static, matching how detection/recsys workloads bucket their sparsity.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply, to_tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "transpose", "reshape", "sum",
    "relu", "tanh", "sin", "asin", "sinh", "asinh", "tan", "atan", "atanh",
    "sqrt", "square", "abs", "pow", "neg", "expm1", "log1p", "cast",
    "coalesce", "nn",
]


def _as_array(x, dtype=None):
    a = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return a.astype(dtype) if dtype is not None else a


class SparseCooTensor:
    """Coordinate-format sparse tensor: indices [sparse_dim, nnz] + values
    [nnz, *dense_dims]."""

    def __init__(self, indices, values: Tensor, shape: Sequence[int],
                 coalesced: bool = False):
        idx = jnp.asarray(indices)
        self._indices = idx if jnp.issubdtype(idx.dtype, jnp.integer) \
            else idx.astype(jnp.int32)
        self._values = values if isinstance(values, Tensor) else to_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = coalesced
        if self._indices.ndim != 2:
            raise ValueError("indices must be [sparse_dim, nnz]")
        if self._indices.shape[1] != self._values.shape[0]:
            raise ValueError(
                f"nnz mismatch: indices {self._indices.shape[1]} vs values "
                f"{self._values.shape[0]}")

    # -- meta --------------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def stop_gradient(self) -> bool:
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v: bool) -> None:
        self._values.stop_gradient = v

    @property
    def sparse_dim(self) -> int:
        return int(self._indices.shape[0])

    @property
    def dense_dim(self) -> int:
        return len(self._shape) - self.sparse_dim

    def nnz(self) -> int:
        return int(self._indices.shape[1])

    def indices(self) -> Tensor:
        return Tensor(self._indices)

    def values(self) -> Tensor:
        return self._values

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return True

    def is_sparse_csr(self) -> bool:
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # -- conversions -------------------------------------------------------
    def to_dense(self) -> Tensor:
        idx = self._indices
        shape = self._shape

        def fn(v):
            out = jnp.zeros(shape, v.dtype)
            return out.at[tuple(idx)].add(v)

        return apply("sparse_coo_to_dense", fn, self._values)

    def coalesce(self) -> "SparseCooTensor":
        return coalesce(self)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._shape) != 2 or self.dense_dim != 0:
            raise ValueError("to_sparse_csr supports 2-D COO tensors")
        coo = coalesce(self)  # sorts rows-major and merges duplicates
        rows, cols = np.asarray(coo._indices)
        m = self._shape[0]
        crows = np.zeros(m + 1, np.int32)
        np.add.at(crows[1:], rows, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(crows, cols, coo._values, self._shape)

    # -- arithmetic sugar --------------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __truediv__(self, other):
        return divide(self, other)

    def __neg__(self):
        return neg(self)

    def T(self):
        return transpose(self, list(range(len(self._shape)))[::-1])

    def astype(self, dtype) -> "SparseCooTensor":
        return cast(self, value_dtype=dtype)

    def numpy(self) -> np.ndarray:
        return np.asarray(self.to_dense()._data)

    def backward(self, *args, **kwargs):
        return self._values.backward(*args, **kwargs)

    @property
    def grad(self):
        return self._values.grad


class SparseCsrTensor:
    """Compressed-sparse-row tensor (2-D): crows [M+1], cols [nnz],
    values [nnz]."""

    def __init__(self, crows, cols, values: Tensor, shape: Sequence[int]):
        crows = jnp.asarray(crows)
        cols = jnp.asarray(cols)
        self._crows = crows if jnp.issubdtype(crows.dtype, jnp.integer) \
            else crows.astype(jnp.int32)
        self._cols = cols if jnp.issubdtype(cols.dtype, jnp.integer) \
            else cols.astype(jnp.int32)
        self._values = values if isinstance(values, Tensor) else to_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise ValueError("SparseCsrTensor supports 2-D shapes")
        if self._crows.shape[0] != self._shape[0] + 1:
            raise ValueError(f"crows must have {self._shape[0] + 1} entries")

    @property
    def shape(self) -> List[int]:
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def nnz(self) -> int:
        return int(self._cols.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return self._values

    def is_sparse(self) -> bool:
        return True

    def is_sparse_coo(self) -> bool:
        return False

    def is_sparse_csr(self) -> bool:
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def _rows(self) -> jnp.ndarray:
        """Expand crows to a per-nnz row index (static total length)."""
        counts = jnp.diff(self._crows)
        return jnp.repeat(jnp.arange(self._shape[0], dtype=jnp.int32), counts,
                          total_repeat_length=self.nnz())

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        idx = jnp.stack([self._rows(), self._cols])
        return SparseCooTensor(idx, self._values, self._shape, coalesced=True)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def numpy(self) -> np.ndarray:
        return np.asarray(self.to_dense()._data)

    def backward(self, *args, **kwargs):
        return self._values.backward(*args, **kwargs)

    @property
    def grad(self):
        return self._values.grad


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------
def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient: bool = True
                      ) -> SparseCooTensor:
    """Parity: paddle.sparse.sparse_coo_tensor."""
    idx = _as_array(indices, jnp.int32)
    vals = values if isinstance(values, Tensor) else to_tensor(
        _as_array(values, dtype))
    if dtype is not None and str(vals.dtype) != str(dtype):
        vals = vals.astype(dtype)
    if shape is None:
        sparse_shape = [int(x) + 1 for x in np.asarray(idx).max(axis=1)]
        shape = sparse_shape + list(vals.shape[1:])
    out = SparseCooTensor(idx, vals, shape)
    out.stop_gradient = stop_gradient
    return out


def sparse_csr_tensor(crows, cols, values, shape: Sequence[int], dtype=None,
                      place=None, stop_gradient: bool = True
                      ) -> SparseCsrTensor:
    """Parity: paddle.sparse.sparse_csr_tensor."""
    vals = values if isinstance(values, Tensor) else to_tensor(
        _as_array(values, dtype))
    out = SparseCsrTensor(_as_array(crows, jnp.int32),
                          _as_array(cols, jnp.int32), vals, shape)
    out._values.stop_gradient = stop_gradient
    return out


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# structural ops
# ---------------------------------------------------------------------------
def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    """Sort indices row-major and sum duplicates. Index bookkeeping is eager
    (NumPy); the values reduction is a dispatched segment-sum, so gradients
    flow."""
    if x._coalesced:
        return x
    idx = np.asarray(x._indices)
    lin = np.ravel_multi_index(idx, x._shape[:x.sparse_dim])
    uniq, inv = np.unique(lin, return_inverse=True)
    new_idx = np.stack(np.unravel_index(uniq, x._shape[:x.sparse_dim]))
    n_out = len(uniq)
    seg = jnp.asarray(inv, jnp.int32)

    def fn(v):
        return jax.ops.segment_sum(v, seg, num_segments=n_out)

    vals = apply("sparse_coalesce", fn, x._values)
    return SparseCooTensor(new_idx, vals, x._shape, coalesced=True)


def transpose(x: SparseCooTensor, perm: Sequence[int]) -> SparseCooTensor:
    """Permute dims (sparse dims only — dense-dim permutes reorder values)."""
    sd = x.sparse_dim
    if sorted(perm) != list(range(len(x._shape))):
        raise ValueError(f"bad perm {perm}")
    sp_perm = [p for p in perm if p < sd]
    if [p for p in perm if p >= sd] != list(range(sd, len(x._shape))):
        raise NotImplementedError("transpose across sparse/dense dims")
    new_idx = x._indices[jnp.asarray(sp_perm)]
    new_shape = tuple(x._shape[p] for p in perm)
    return SparseCooTensor(new_idx, x._values, new_shape)


def reshape(x: SparseCooTensor, shape: Sequence[int]) -> SparseCooTensor:
    """Reshape over the sparse dims (dense part unchanged)."""
    sd = x.sparse_dim
    sp_shape = x._shape[:sd]
    new_sp = tuple(int(s) for s in shape[:len(shape) - x.dense_dim])
    if int(np.prod(sp_shape)) != int(np.prod(new_sp)):
        raise ValueError("reshape must preserve sparse volume")
    lin = jnp.ravel_multi_index(tuple(x._indices), sp_shape, mode="clip")
    new_idx = jnp.stack(jnp.unravel_index(lin, new_sp))
    return SparseCooTensor(new_idx, x._values,
                           new_sp + x._shape[sd:], x._coalesced)


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------
def _same_pattern(a: SparseCooTensor, b: SparseCooTensor) -> bool:
    return (a._indices.shape == b._indices.shape and
            bool(jnp.all(a._indices == b._indices)))


def _binary(a, b, op_name, fn, union_fn=None):
    if isinstance(a, SparseCooTensor) and isinstance(b, SparseCooTensor):
        if list(a._shape) != list(b._shape):
            raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
        ac, bc = coalesce(a), coalesce(b)
        if _same_pattern(ac, bc):
            vals = apply(f"sparse_{op_name}", fn, ac._values, bc._values)
            return SparseCooTensor(ac._indices, vals, ac._shape, True)
        if union_fn is None:
            raise ValueError(
                f"sparse {op_name} requires matching sparsity patterns")
        return union_fn(ac, bc)
    if isinstance(a, SparseCooTensor) and isinstance(b, (int, float)):
        vals = apply(f"sparse_{op_name}", lambda v: fn(v, b), a._values)
        return SparseCooTensor(a._indices, vals, a._shape, a._coalesced)
    if isinstance(a, SparseCooTensor) and isinstance(b, Tensor):
        if op_name in ("add", "sub"):
            # add/sub against dense would need values everywhere, i.e. a
            # densified result — the reference disallows it too
            raise TypeError(
                f"sparse {op_name} with a dense Tensor would densify; "
                "convert with to_dense() first")
        # mul/div: zeros outside the pattern stay zero, so computing at the
        # stored sites is exact
        idx = a._indices
        vals = apply(f"sparse_{op_name}_dense",
                     lambda v, d: fn(v, d[tuple(idx)]), a._values, b)
        return SparseCooTensor(a._indices, vals, a._shape, a._coalesced)
    raise TypeError(f"unsupported operands for sparse {op_name}")


def _union_add(sign: float):
    def impl(ac: SparseCooTensor, bc: SparseCooTensor) -> SparseCooTensor:
        idx = jnp.concatenate([ac._indices, bc._indices], axis=1)
        if sign == 1.0:
            vals = apply("sparse_concat",
                         lambda u, v: jnp.concatenate([u, v]), ac._values,
                         bc._values)
        else:
            vals = apply("sparse_concat",
                         lambda u, v: jnp.concatenate([u, -v]), ac._values,
                         bc._values)
        return coalesce(SparseCooTensor(idx, vals, ac._shape))
    return impl


def add(a, b):
    return _binary(a, b, "add", lambda u, v: u + v, _union_add(1.0))


def subtract(a, b):
    return _binary(a, b, "sub", lambda u, v: u - v, _union_add(-1.0))


def multiply(a, b):
    return _binary(a, b, "mul", lambda u, v: u * v)


def divide(a, b):
    return _binary(a, b, "div", lambda u, v: u / v)


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------
def matmul(a, b) -> Tensor:
    """sparse @ dense → dense. COO/CSR 2-D × dense 2-D: a gather +
    segment-sum contraction (the TPU lowering of SpMM)."""
    if isinstance(a, SparseCsrTensor):
        a = a.to_sparse_coo()
    if not isinstance(a, SparseCooTensor) or not isinstance(b, Tensor):
        raise TypeError("matmul expects (sparse, dense Tensor)")
    if len(a._shape) != 2 or a.dense_dim != 0:
        raise ValueError("sparse matmul supports 2-D sparse operands")
    rows, cols = a._indices[0], a._indices[1]
    m = a._shape[0]

    def fn(v, d):
        contrib = v[:, None] * d[cols]  # [nnz, N]
        return jax.ops.segment_sum(contrib, rows, num_segments=m)

    return apply("sparse_matmul", fn, a._values, b)


def masked_matmul(a: Tensor, b: Tensor, mask) -> Union[SparseCooTensor,
                                                       SparseCsrTensor]:
    """(a @ b) sampled at the sparsity pattern of ``mask`` (SDDMM)."""
    is_csr = isinstance(mask, SparseCsrTensor)
    coo = mask.to_sparse_coo() if is_csr else mask
    rows, cols = coo._indices[0], coo._indices[1]

    def fn(x, y):
        return jnp.einsum("nk,nk->n", x[rows], y[:, cols].T)

    vals = apply("sparse_masked_matmul", fn, a, b)
    if is_csr:
        return SparseCsrTensor(mask._crows, mask._cols, vals, mask._shape)
    return SparseCooTensor(coo._indices, vals, coo._shape, coo._coalesced)


def sum(x: SparseCooTensor, axis: Optional[int] = None, dtype=None,
        keepdim: bool = False):
    """Reduce a COO tensor. Full reduction returns a dense scalar Tensor;
    axis reductions return a dense Tensor (the reference returns sparse —
    densifying is the XLA-friendly choice and documented divergence)."""
    if axis is None:
        out = apply("sparse_sum", lambda v: jnp.sum(v), x._values)
        return out.astype(dtype) if dtype is not None else out
    out = x.to_dense().sum(axis=axis, keepdim=keepdim)
    return out.astype(dtype) if dtype is not None else out


# ---------------------------------------------------------------------------
# unary value ops (pattern-preserving)
# ---------------------------------------------------------------------------
def _unary(name, fn):
    def op(x, *args):  # args are static scalars (e.g. pow exponent)
        vals = apply(f"sparse_{name}",
                     (lambda v: fn(v, *args)) if args else fn, x._values)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
        raise TypeError(f"sparse.{name} expects a sparse tensor")
    op.__name__ = name
    return op


relu = _unary("relu", lambda v: jnp.maximum(v, 0))
tanh = _unary("tanh", jnp.tanh)
sin = _unary("sin", jnp.sin)
asin = _unary("asin", jnp.arcsin)
sinh = _unary("sinh", jnp.sinh)
asinh = _unary("asinh", jnp.arcsinh)
tan = _unary("tan", jnp.tan)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)


def pow(x, exponent):
    return _unary("pow", jnp.power)(x, exponent)


def cast(x, index_dtype=None, value_dtype=None):
    vals = x._values.astype(value_dtype) if value_dtype is not None else \
        x._values
    if isinstance(x, SparseCooTensor):
        idx = x._indices.astype(index_dtype) if index_dtype is not None \
            else x._indices
        return SparseCooTensor(idx, vals, x._shape, x._coalesced)
    crows = x._crows.astype(index_dtype) if index_dtype is not None \
        else x._crows
    cols = x._cols.astype(index_dtype) if index_dtype is not None else x._cols
    return SparseCsrTensor(crows, cols, vals, x._shape)


from . import nn  # noqa: E402,F401


# ---------------------------------------------------------------------------
# round-3 surface wave: mv / addmm / slice + unary tail
# (upstream python/paddle/sparse/ + paddle/phi/kernels/sparse/)
# ---------------------------------------------------------------------------

def mv(a, x) -> Tensor:
    """sparse (M, N) @ dense vector (N,) -> dense (M,)."""
    if not isinstance(x, Tensor) or x._data.ndim != 1:
        raise TypeError("sparse.mv expects a dense 1-D vector")
    from ..ops.manipulation import reshape as _reshape
    out = matmul(a, _reshape(x, [-1, 1]))
    return _reshape(out, [-1])


def addmm(input, x, y, beta=1.0, alpha=1.0) -> Tensor:
    """beta * input + alpha * (x @ y) with sparse ``x`` (reference:
    paddle.sparse.addmm's sparse-dense-dense form)."""
    prod = matmul(x, y)
    return input * beta + prod * alpha


def slice(x, axes, starts, ends):
    """Slice a COO tensor along ``axes`` (reference: paddle.sparse.slice).
    Pattern-level filter: rows whose coordinates fall inside the window
    keep their values with shifted indices."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.slice expects a sparse tensor")
    x = coalesce(x)
    idx = np.asarray(x._indices)
    vals = x._values
    shape = list(x._shape)
    keep = np.ones(idx.shape[1], bool)
    for ax, s, e in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        s = int(s) + shape[ax] if int(s) < 0 else int(s)
        e = int(e) + shape[ax] if int(e) < 0 else int(e)
        e = min(e, shape[ax])
        keep &= (idx[ax] >= s) & (idx[ax] < e)
        shape[ax] = e - s
    sel = np.where(keep)[0]
    new_idx = idx[:, sel]
    for ax, s, _e in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        s = int(s) + x._shape[ax] if int(s) < 0 else int(s)
        new_idx[ax] = new_idx[ax] - s
    from ..core.tensor import apply as _apply
    new_vals = _apply("sparse_slice_gather",
                      lambda v: v[jnp.asarray(sel)], vals)
    return SparseCooTensor(jnp.asarray(new_idx), new_vals, shape, True)


isnan = _unary("isnan", jnp.isnan)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)

def mask_as(x, mask, name=None):
    """Take dense ``x``'s entries at ``mask``'s sparsity pattern (parity:
    paddle.sparse.mask_as) — returns a sparse tensor with mask's layout
    and x's values."""
    from ..ops._helpers import ensure_tensor
    x = ensure_tensor(x)
    mshape = getattr(mask, "_shape", None)
    if mshape is not None and tuple(x._data.shape) != tuple(mshape):
        # jax gathers CLAMP out-of-range indices — a shape mismatch would
        # silently duplicate edge values instead of erroring (reference
        # raises on mismatched shapes)
        raise ValueError(f"mask_as shape mismatch: x {tuple(x._data.shape)} "
                         f"vs mask {tuple(mshape)}")
    if isinstance(mask, SparseCsrTensor):
        rows, cols = mask._rows(), mask._cols
        vals = apply("sparse_mask_as", lambda a: a[rows, cols], x)
        return SparseCsrTensor(mask._crows, mask._cols, vals, mask._shape)
    if isinstance(mask, SparseCooTensor):
        idx = mask._indices
        vals = apply("sparse_mask_as", lambda a: a[tuple(idx)], x)
        return SparseCooTensor(idx, vals, mask._shape, mask._coalesced)
    raise ValueError("mask must be a sparse COO/CSR tensor")


__all__ += ["mv", "addmm", "slice", "isnan", "rad2deg", "deg2rad", "mask_as"]
