"""to_static: trace → functionalize → jax.jit with state donation."""

from __future__ import annotations

import functools
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import (Tensor, TraceBreakError, _state_registry,
                           _is_tracer)
from .. import flags as _flags
from .. import observability as _obs
from ..core.tracing import (TraceState, pop_trace_state, push_trace_state,
                            trace_state)

__all__ = ["StaticFunction", "to_static", "not_to_static", "ignore_module",
           "register_pretrace_hook", "TraceBreakError"]

_ENABLED = True

_FALLBACK = object()  # cache sentinel: this signature graph-breaks to eager
_SEGMENTED = object()  # cache sentinel: run via lazy compiled segments

# serializes trace/invoke/rebind across threads (ISSUE 15: in-process
# multi-replica serving runs one step thread per engine): the global state
# registry is threaded through every compiled call, so interleaved calls
# would capture each other's tracers. RLock — a dead-state rebuild or a
# nested fallback re-enters on the same thread.
_INVOKE_LOCK = threading.RLock()

# ISSUE 16: compile-time cost capture. observability.cost installs a
# callable here while enabled (the _op_metrics_hook is-None contract: the
# build path pays one probe when off, and analysis — a second AOT
# compile — runs only for fresh builds while the hook is live).
# Signature: hook("build", sf, jitted=, state_specs=, arg_specs=, key=)
# on a fresh successful build; hook("retire", sf, key=) when a dead-state
# entry is dropped before its retrace.
_cost_hook: Optional[Callable] = None


def _lower_spec(a):
    """ShapeDtypeStruct for lowering outside the live call. Single-device
    shardings mean "uncommitted" here — passing them into lower() would
    conflict with in-step mesh constraints, which the real call
    (uncommitted arrays) never does."""
    sh = getattr(a, "sharding", None)
    if not isinstance(sh, jax.sharding.NamedSharding):
        sh = None
    try:
        return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
    except TypeError:
        return jax.ShapeDtypeStruct(a.shape, a.dtype)


def _is_trace_failure(e: BaseException) -> bool:
    """Graph breaks are TRACE/LOWERING failures only (tensor-dependent Python
    control flow, tracer leaks, ops without abstract eval) — the reference
    SOT's fallback contract. Runtime failures (XLA execution errors, device
    OOM, asserts that only fire under jit) must NOT memoize a permanent
    eager fallback: they re-raise so the user sees them."""
    return isinstance(e, (jax.errors.JAXTypeError,
                          jax.errors.NonConcreteBooleanIndexError,
                          NotImplementedError, TraceBreakError))

# Objects with lazily-derived state (e.g. optimizer AMP masters) register here;
# before any (re)trace we give them a chance to reconcile derived state with
# concrete values — inside the trace the data is symbolic and it's too late.
_pretrace_refs: List = []


def register_pretrace_hook(obj) -> None:
    with _INVOKE_LOCK:
        _pretrace_refs.append(weakref.ref(obj))


def _run_pretrace_hooks_locked() -> None:
    """Caller holds ``_INVOKE_LOCK`` (the ``_call_locked`` path)."""
    alive = []
    for r in _pretrace_refs:
        o = r()
        if o is not None:
            alive.append(r)
            o._refresh_derived_state()
    _pretrace_refs[:] = alive


def _set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def _is_tensor(x) -> bool:
    return isinstance(x, Tensor)


class StaticFunction:
    """Callable wrapping ``fn`` with whole-step XLA compilation.

    Functionalization contract:
    * every live registered state tensor (params, buffers, optimizer
      accumulators, RNG keys) becomes a jit input AND a jit output — outputs
      for un-mutated state are aliases of the donated inputs, so donation is
      always safe (every state tensor is rebound to a live buffer after the
      call; nothing is left pointing at a deleted donated array);
    * additional mutated locations discovered while tracing (``.grad`` slots,
      non-registered tensors) ride along as extra outputs via the holder spec.
    * cache entries hold only WEAK references to state tensors; the cache key
      is the tuple of registry ids, so a discarded model's entry can never be
      hit again and its parameter arrays are free to be collected.
    """

    def __init__(self, fn: Callable, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True, donate_states: bool = True,
                 iters_per_call: int = 1):
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._donate = donate_states
        # full_graph=False is the reference SOT contract: a trace failure
        # (tensor-dependent Python control flow) switches the signature to
        # PARTIAL-GRAPH capture — the lazy segment executor (core/lazy.py)
        # compiles the op runs around each break and re-runs Python as the
        # control-flow interpreter, like upstream SOT's
        # subgraph-with-guards. Our default stays strict (full_graph=True)
        # because the silent perf change is usually a bug the user wants
        # to see.
        self._full_graph = bool(full_graph)
        self._warned_fallback = False
        if not self._full_graph:
            # fallback may re-run the fn eagerly after a compiled attempt
            # failed mid-flight; donation would have deleted the state
            # buffers that eager rerun reads — the compatibility mode
            # trades donation for a safe graph-break
            self._donate = False
        # iters_per_call > 1: lax.scan ``fn`` over the leading axis of every
        # tensor argument inside ONE compiled call (state is the scan carry).
        # This is the standard TPU scan-over-steps trainer pattern — it
        # amortizes per-dispatch overhead (which on a remote-attached chip is
        # ~20ms/call for a model-sized buffer set) across K steps. The fn is
        # still written per-step; the caller passes K-stacked inputs.
        self._iters = int(iters_per_call)
        self._cache: Dict[Any, Tuple] = {}
        self.concrete_program = None  # parity attribute
        self._last_lowered = None  # (jitted, arg shape/sharding specs)
        # ISSUE 16: cost-record identity. Owners that know what this
        # program IS (step_capture, the serving engine) set site/label so
        # the cost registry files its records under the right name;
        # unset means a generic "jit" program. cost_analytic_flops is the
        # flops_counter-style fallback used when XLA has no cost model.
        self.cost_site: Optional[str] = None
        self.cost_label: Optional[str] = None
        self.cost_analytic_flops: Optional[float] = None
        # (cache key, arg aval signature) pairs already captured: one
        # cache entry's jax.jit respecializes per input shape (the
        # serving engine's batch buckets), so "fresh build" alone would
        # miss every executable after the first
        self._cost_captured: set = set()

    @property
    def program_cache(self):
        return self._cache

    def compiled_text(self) -> str:
        """XLA-compiled HLO of the most recent call (requires the
        FLAGS_to_static_capture_lowered debug flag). Test/debug surface for
        asserting on the compiled program, e.g. that ZeRO sharding lowered
        to reduce-scatter rather than a full all-reduce."""
        if self._last_lowered is None:
            raise RuntimeError(
                "no lowered call captured; set "
                "paddle.set_flags({'FLAGS_to_static_capture_lowered': True}) "
                "and invoke the function first")
        jitted, state_specs, arg_specs = self._last_lowered
        return jitted.lower(state_specs, arg_specs).compile().as_text()

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = functools.partial(self.__call__, instance)
        functools.update_wrapper(bound, self._fn)
        return bound

    def __call__(self, *args, **kwargs):
        if not _ENABLED or trace_state() is not None:
            # nested to_static or globally disabled -> run eagerly/inline
            if self._iters > 1:
                return self._run_iters_eager(args, kwargs)
            return self._fn(*args, **kwargs)
        # one compiled call at a time, PROCESS-WIDE (ISSUE 15): every
        # StaticFunction threads the same global state registry (params,
        # RNG key) through trace + post-call rebinding — two threads (e.g.
        # two serving replicas in one process) interleaving here leak each
        # other's tracers into the registry. Reentrant, so a rebuild
        # recursion or a nested eager fallback on the SAME thread is fine;
        # uncontended for every single-threaded caller.
        with _INVOKE_LOCK:
            return self._call_locked(*args, **kwargs)

    def _call_locked(self, *args, **kwargs):
        # runs on every call (not just cache misses): a state_dict load after
        # compilation must be reconciled into derived state (fp32 masters)
        # BEFORE the compiled step reads it — masters are carried state, so a
        # data refresh needs no retrace
        _run_pretrace_hooks_locked()

        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=_is_tensor)
        arg_arrays: List[Any] = []
        proto: List[Any] = []  # per-leaf: Tensor template | None (raw array) | _STATIC
        statics: List[Any] = []
        for leaf in leaves:
            if isinstance(leaf, Tensor):
                arg_arrays.append(leaf._data)
                proto.append(leaf)
            elif isinstance(leaf, (jax.Array, np.ndarray)) and not isinstance(leaf, np.bool_):
                arg_arrays.append(jnp.asarray(leaf))
                proto.append(None)
            else:
                statics.append(leaf)
                proto.append(_STATIC)

        if self._iters > 1:
            for arr in arg_arrays:
                if arr.ndim == 0 or arr.shape[0] != self._iters:
                    raise ValueError(
                        f"iters_per_call={self._iters}: every tensor argument "
                        f"must be stacked with leading dim {self._iters}, got "
                        f"shape {tuple(arr.shape)}")

        state_items = _state_registry.alive_items()  # [(regid, tensor)]
        try:
            static_key = tuple(statics)
            hash(static_key)
        except TypeError:
            static_key = tuple(repr(s) for s in statics)
        key = (treedef, static_key, tuple(rid for rid, _ in state_items))
        entry = self._cache.get(key)
        if entry is None:
            # hooks may touch the registry; recompute the key before building
            state_items = _state_registry.alive_items()
            key = (treedef, static_key, tuple(rid for rid, _ in state_items))
            entry = self._cache.get(key)
        if entry is _SEGMENTED:
            return self._run_segmented(args, kwargs, key)
        if entry is _FALLBACK:
            # memoized graph break (full_graph=False): skip re-tracing
            if self._iters > 1:
                return self._run_iters_eager(args, kwargs)
            return self._fn(*args, **kwargs)
        fresh_build = entry is None
        if fresh_build:
            _obs.inc("jit.cache_misses_total")
            entry = self._build(treedef, proto, statics,
                                [t for _, t in state_items])
            self._cache[key] = entry
        jitted, state_refs, holder = entry

        state_tensors = [r() for r in state_refs]
        if any(t is None for t in state_tensors):
            # a state tensor died between building and calling (rare): rebuild
            cost_hook = _cost_hook
            if cost_hook is not None:
                cost_hook("retire", self, key=key)
            self._cost_captured = {c for c in self._cost_captured
                                   if c[0] != key}
            del self._cache[key]
            return self.__call__(*args, **kwargs)
        if not fresh_build:
            # counted AFTER the dead-state check: a stale entry that forces
            # the rebuild recursion above is one logical call, not a hit
            # plus a miss
            _obs.inc("jit.cache_hits_total")

        # cost capture needs the argument specs from BEFORE the call —
        # donation deletes the very buffers the specs describe. Keyed on
        # (cache key, arg aval signature), not fresh_build: one entry's
        # jax.jit compiles a NEW executable per input shape (serving
        # batch buckets), and each deserves its own cost record.
        cost_hook = _cost_hook
        cost_specs = cost_key = None
        if cost_hook is not None:
            sig = tuple((tuple(a.shape), str(a.dtype)) for a in arg_arrays)
            if (key, sig) not in self._cost_captured:
                cost_key = (key, sig)
                cost_specs = ([_lower_spec(t._data) for t in state_tensors],
                              [_lower_spec(a) for a in arg_arrays])
        try:
            result = self._invoke(jitted, holder, state_tensors, arg_arrays,
                                  leaves, key)
            if fresh_build:
                # counted on SUCCESS, not at _build: a first call that
                # graph-breaks discards the executable without XLA ever
                # compiling it, and must not read as a compile
                _obs.inc("jit.compiles_total")
            if cost_specs is not None:
                self._cost_captured.add(cost_key)
                cost_hook("build", self, jitted=jitted,
                          state_specs=cost_specs[0],
                          arg_specs=cost_specs[1], key=key,
                          sig=cost_key[1])
            return result
        except Exception as e:
            if self._full_graph or not _is_trace_failure(e):
                # full-graph mode, or a genuine runtime failure (XLA execution
                # error, assert under jit): surface it — only trace failures
                # are graph breaks
                raise
            # SOT-style graph break (upstream python/paddle/jit/sot/):
            # tracing failed (tensor-dependent Python control flow,
            # unsupported op). Partial-graph capture: re-run through the
            # lazy segment executor — compiled segments around the break,
            # Python as the control-flow interpreter (core/lazy.py). Falls
            # back to plain eager only if segmenting itself fails.
            _obs.inc("jit.graph_breaks_total")
            if self._iters > 1:
                self._cache[key] = _FALLBACK
                self._warn_break(e, "eager execution (iters_per_call)")
                return self._run_iters_eager(args, kwargs)
            self._cache[key] = _SEGMENTED
            self._warn_break(e, "compiled-segment execution")
            return self._run_segmented(args, kwargs, key)

    def _warn_break(self, e, how: str) -> None:
        if not self._warned_fallback:
            import warnings
            warnings.warn(
                f"to_static(full_graph=False): tracing "
                f"{getattr(self._fn, '__name__', '?')} failed "
                f"({type(e).__name__}: {e}); falling back to {how}")
            self._warned_fallback = True

    def _run_segmented(self, args, kwargs, key):
        """Graph-break mode: execute through the lazy segment recorder —
        device work runs as cached compiled segments split at concrete
        reads; Python runs every call and owns the control flow."""
        from ..core import lazy as _lazy
        try:
            with _lazy.segment_mode():
                return self._fn(*args, **kwargs)
        except Exception as e:
            # segment_mode.__exit__ flushed whatever had been recorded, so
            # state mutations up to the failure are applied exactly once —
            # re-running the fn here would double-apply them, so we never
            # do. A LAZY-MACHINERY failure (an op touching the placeholder
            # in a way the recorder can't stage) downgrades FUTURE calls to
            # plain eager; genuine user errors keep the segmented path.
            if ("LazyValue" in str(e) or isinstance(e, NotImplementedError)
                    or isinstance(e, jax.errors.UnexpectedTracerError)):
                self._cache[key] = _FALLBACK
                import warnings
                warnings.warn(
                    f"to_static(full_graph=False): segmented execution of "
                    f"{getattr(self._fn, '__name__', '?')} cannot stage this "
                    f"function ({type(e).__name__}: {e}); later calls run "
                    "plain eager")
            raise

    def _invoke(self, jitted, holder, state_tensors, arg_arrays, leaves,
                key):
        state_arrays = [t._data for t in state_tensors]
        if _flags.flag("to_static_capture_lowered"):
            self._last_lowered = (jitted,
                                  [_lower_spec(a) for a in state_arrays],
                                  [_lower_spec(a) for a in arg_arrays])
        if self._donate:
            # donated buffers must be unique: two state tensors aliasing one
            # jax.Array (or a state array that is also a plain argument) make
            # XLA reject the executable call on TPU. Copy the duplicates so
            # every donated slot owns its buffer.
            seen = {id(a) for a in arg_arrays}
            for i, a in enumerate(state_arrays):
                if id(a) in seen:
                    state_arrays[i] = jnp.copy(a)
                else:
                    seen.add(id(a))
        out_arrays, new_state, mut_vals = jitted(state_arrays, arg_arrays)
        for t, arr in zip(state_tensors, new_state):
            t._data = arr
        self._rebind(holder, mut_vals, leaves)
        return _wrap_outputs(out_arrays)

    def _run_iters_eager(self, args, kwargs):
        """Eager-mode equivalent of the scan: slice the K-stacked tensor args
        and run fn per step, stacking the outputs — so a debug run with
        to_static disabled keeps the compiled run's semantics."""
        def _is_sliceable(x):
            return (isinstance(x, Tensor) or
                    (isinstance(x, (jax.Array, np.ndarray))
                     and getattr(x, "ndim", 0) > 0))

        def slice_leaf(i):
            # slice the same leaves the compiled path scans over: Tensors AND
            # raw arrays (both land in arg_arrays there)
            return lambda x: x[i] if _is_sliceable(x) else x

        def stack_leaf(*xs):
            if isinstance(xs[0], Tensor):
                return Tensor(jnp.stack([x._data for x in xs]),
                              stop_gradient=True)
            if isinstance(xs[0], (jax.Array, np.ndarray)):
                return jnp.stack([jnp.asarray(x) for x in xs])
            return xs[0]

        outs = []
        for i in range(self._iters):
            a_i, k_i = jax.tree_util.tree_map(
                slice_leaf(i), (args, kwargs), is_leaf=_is_tensor)
            outs.append(self._fn(*a_i, **k_i))
        return jax.tree_util.tree_map(stack_leaf, *outs, is_leaf=_is_tensor)

    # -------------------------------------------------------------------------
    def _build(self, treedef, proto, statics, state_tensors):
        _obs.inc("jit.traces_total")
        if self._iters > 1:
            return self._build_scan(treedef, proto, statics, state_tensors)
        holder: Dict[str, Any] = {"spec": None}
        fn = self._fn
        state_refs = [weakref.ref(t) for t in state_tensors]
        state_ids = {id(t) for t in state_tensors}

        def pure_fn(state_arrays, arg_arrays):
            tensors = [r() for r in state_refs]
            saved_state = [t._data for t in tensors]
            for t, arr in zip(tensors, state_arrays):
                t._data = arr
            ts = TraceState()
            push_trace_state(ts)
            try:
                arg_pos = {}  # id(inner arg Tensor) -> leaf position
                args2, kwargs2 = _rebuild_args(proto, statics, arg_arrays,
                                               treedef, arg_pos)
                out = fn(*args2, **kwargs2)
                out_arrays = jax.tree_util.tree_map(
                    lambda x: x._data if isinstance(x, Tensor) else x, out,
                    is_leaf=_is_tensor)
                # all state is carried through (un-mutated entries become
                # input->output aliases under donation)
                new_state = [t._data for t in tensors]
                # extra mutated locations not covered by the state carry
                spec = []
                mut_vals = []
                for kind, ref in ts.mutations:
                    tt = ref()
                    if tt is None:
                        continue
                    if kind == "data":
                        if id(tt) in state_ids:
                            continue  # carried via new_state
                        val = tt._data
                    else:
                        g = tt._grad
                        val = None if g is None else g._data
                    if val is not None and not _is_tracer(val):
                        val = jnp.asarray(val)
                    if id(tt) in arg_pos:
                        # mutation of a traced ARG tensor: rebind onto the
                        # caller's tensor for that leaf position at call time
                        # (paddle parity: x.grad lands on the passed-in x)
                        spec.append((f"arg_{kind}", arg_pos[id(tt)]))
                    else:
                        spec.append((kind, ref))
                    mut_vals.append(val)
                holder["spec"] = spec
                return out_arrays, new_state, mut_vals
            finally:
                pop_trace_state()
                ts.restore()
                for t, arr in zip(tensors, saved_state):
                    t._data = arr

        donate = (0,) if self._donate else ()
        jitted = jax.jit(pure_fn, donate_argnums=donate)
        return jitted, state_refs, holder

    def _build_scan(self, treedef, proto, statics, state_tensors):
        """iters_per_call mode: scan the per-step fn over K-stacked args.

        Constraint: every per-step mutation must either be registered state
        (rides the scan carry) or resolve to None by step end (grads after
        ``clear_grad``) — anything else cannot escape the scan body.
        """
        holder: Dict[str, Any] = {"spec": None}
        fn = self._fn
        state_refs = [weakref.ref(t) for t in state_tensors]
        state_ids = {id(t) for t in state_tensors}

        def pure_fn(state_arrays, arg_arrays):
            tensors = [r() for r in state_refs]
            saved_state = [t._data for t in tensors]

            def body(carry, xs):
                for t, arr in zip(tensors, carry):
                    t._data = arr
                ts = TraceState()
                push_trace_state(ts)
                try:
                    args2, kwargs2 = _rebuild_args(proto, statics, xs, treedef)
                    out = fn(*args2, **kwargs2)
                    out_arrays = jax.tree_util.tree_map(
                        lambda x: x._data if isinstance(x, Tensor) else x, out,
                        is_leaf=_is_tensor)
                    spec = []
                    for kind, ref in ts.mutations:
                        tt = ref()
                        if tt is None:
                            continue
                        if kind == "data":
                            if id(tt) in state_ids:
                                continue
                            if _is_tracer(tt._data):
                                raise RuntimeError(
                                    "iters_per_call: the step mutates a "
                                    f"non-state tensor ({tt.name or 'unnamed'})"
                                    "; register it as state or drop "
                                    "iters_per_call")
                            continue  # concrete host-side write: ignore
                        g = tt._grad
                        if g is not None and _is_tracer(g._data):
                            raise RuntimeError(
                                "iters_per_call: gradients must be cleared "
                                "within the step (call opt.clear_grad()) so "
                                "no per-step value escapes the scan")
                        spec.append(("grad", ref))
                    holder["spec"] = spec
                    new_state = [t._data for t in tensors]
                    return new_state, out_arrays
                finally:
                    pop_trace_state()
                    ts.restore()
                    for t, arr in zip(tensors, saved_state):
                        t._data = arr

            final_state, outs = jax.lax.scan(body, list(state_arrays),
                                             list(arg_arrays),
                                             length=self._iters)
            mut_vals = [None] * len(holder["spec"] or [])
            return outs, final_state, mut_vals

        donate = (0,) if self._donate else ()
        jitted = jax.jit(pure_fn, donate_argnums=donate)
        return jitted, state_refs, holder

    @staticmethod
    def _rebind(holder, mut_vals, leaves=None) -> None:
        spec = holder["spec"] or []
        for (kind, ref), val in zip(spec, mut_vals):
            if kind.startswith("arg_"):
                tt = leaves[ref] if leaves is not None else None
                kind = kind[4:]
            else:
                tt = ref()
            if tt is None or not isinstance(tt, Tensor):
                continue
            if kind == "data":
                if val is not None:
                    tt._data = val
            else:
                if val is None:
                    tt._grad = None
                elif tt._grad is None:
                    tt._grad = Tensor(val, stop_gradient=True)
                else:
                    tt._grad._data = val


class _StaticMarker:
    __slots__ = ()


_STATIC = _StaticMarker()


def _rebuild_args(proto, statics, arrays, treedef, arg_pos=None):
    """Reconstruct the traced call's (args, kwargs) from the flat pieces:
    per-leaf proto (Tensor template | None | _STATIC), static values, and the
    traced arrays. Shared by the single-step and scan build paths."""
    it_arr = iter(arrays)
    it_static = iter(statics)
    leaves = []
    for pos, p in enumerate(proto):
        if p is _STATIC:
            leaves.append(next(it_static))
        elif p is None:
            leaves.append(next(it_arr))
        else:
            t = Tensor(next(it_arr), stop_gradient=p.stop_gradient,
                       name=p.name)
            if arg_pos is not None:
                arg_pos[id(t)] = pos
            leaves.append(t)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _wrap_outputs(out):
    return jax.tree_util.tree_map(
        lambda x: Tensor(x, stop_gradient=True)
        if isinstance(x, jax.Array) else x, out)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    """``paddle.jit.to_static`` parity decorator."""

    sf_kwargs = {k: kwargs[k]
                 for k in ("iters_per_call", "donate_states", "full_graph")
                 if k in kwargs}

    def decorate(fn):
        # Layers: wrap forward, return the layer (paddle semantics)
        from ..nn.layer import Layer
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec, build_strategy,
                                        backend, **sf_kwargs)
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              **sf_kwargs)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    """Marker parity: functions excluded from capture simply run inline."""
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules) -> None:
    """Parity no-op: our tracing never descends into foreign modules'
    internals anyway (jax handles them natively or they fail loudly)."""
