"""``paddle.jit``: whole-step compilation.

Parity surface: python/paddle/jit/ (``to_static`` — upstream implemented as
SOT bytecode capture / AST transform building a PIR program executed by the
StandaloneExecutor + CINN; see SURVEY.md §3.2). TPU-native design: the user
function is *functionalized* — every live framework-state tensor (parameters,
buffers, optimizer accumulators, RNG key) becomes a jit input, the traced
body records which state locations it mutates, and those become jit outputs
that are rebound after each call. The result is ONE fused XLA program per
train step with buffer donation on the state (in-place optimizer semantics),
which is where TPU performance lives.

``capture_step`` (ISSUE 11) is the train-step-shaped surface over the same
machinery: forward + backward + optimizer update captured as one donated
program behind ``PADDLE_TPU_STEP_CAPTURE=auto|off``, with structural-
signature + flags-epoch re-trace keying, NaN-gated in-program updates, and
``train.capture_*`` observability — what ``hapi.Model.fit`` and the PR 10
``TrainingSupervisor`` ride (``core/step_capture.py``).
"""

from .to_static import (StaticFunction, TraceBreakError, to_static,  # noqa: F401
                        not_to_static, ignore_module)
from ..core.step_capture import (CapturedStep, HostStateWriteError,  # noqa: F401
                                 capture_step)
from .save_load import save, load, TranslatedLayer  # noqa: F401


def enable_to_static(flag: bool = True) -> None:
    from .to_static import _set_enabled
    _set_enabled(flag)


def set_code_level(level: int = 100, also_to_stdout: bool = False) -> None:
    """Parity shim: Dy2Static transformed-code dumping. This build traces
    the original python directly (no generated code to print); the level is
    recorded for API compatibility."""
    global _code_level
    _code_level = level


def set_verbosity(level: int = 0, also_to_stdout: bool = False) -> None:
    global _verbosity
    _verbosity = level


_code_level = 0
_verbosity = 0


class TracedLayer:
    """Parity: paddle.jit.TracedLayer — trace a layer once, replay the
    compiled program. Wraps ``to_static`` (the trace IS the jaxpr program).
    """

    def __init__(self, layer, static_fn):
        self._layer = layer
        self._fn = static_fn

    @staticmethod
    def trace(layer, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        fn = to_static(lambda *xs: layer(*xs))
        outs = fn(*inputs)
        return outs, TracedLayer(layer, fn)

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        from . import save as _save
        _save(self._layer, path)
