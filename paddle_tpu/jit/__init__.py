"""``paddle.jit``: whole-step compilation.

Parity surface: python/paddle/jit/ (``to_static`` — upstream implemented as
SOT bytecode capture / AST transform building a PIR program executed by the
StandaloneExecutor + CINN; see SURVEY.md §3.2). TPU-native design: the user
function is *functionalized* — every live framework-state tensor (parameters,
buffers, optimizer accumulators, RNG key) becomes a jit input, the traced
body records which state locations it mutates, and those become jit outputs
that are rebound after each call. The result is ONE fused XLA program per
train step with buffer donation on the state (in-place optimizer semantics),
which is where TPU performance lives.
"""

from .to_static import StaticFunction, to_static, not_to_static, ignore_module  # noqa: F401
from .save_load import save, load, TranslatedLayer  # noqa: F401


def enable_to_static(flag: bool = True) -> None:
    from .to_static import _set_enabled
    _set_enabled(flag)
