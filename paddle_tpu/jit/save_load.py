"""``paddle.jit.save`` / ``paddle.jit.load``.

Parity surface: python/paddle/jit/api.py jit.save (inference program +
params) and paddle.jit.load (TranslatedLayer). TPU-native: the "program" is a
serialized StableHLO module exported with ``jax.export`` from the traced
forward; params ride alongside as a pickled state_dict. Loading rebuilds a
callable TranslatedLayer that executes the XLA program.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..framework.io import _pack, _unpack

__all__ = ["save", "load", "TranslatedLayer"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtype
        self.name = name


def save(layer, path: str, input_spec: Optional[List[Any]] = None, **configs) -> None:
    """Serialize ``layer`` for inference: StableHLO program + params."""
    from ..nn.layer import Layer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if not isinstance(layer, Layer):
        raise TypeError("paddle.jit.save expects an nn.Layer")
    state = layer.state_dict()
    names = list(state)
    param_arrays = [np.asarray(state[n]._data) for n in names]

    exported_bytes = None
    if input_spec:
        specs = []
        for s in input_spec:
            if isinstance(s, InputSpec):
                shape = tuple(1 if d == -1 else d for d in s.shape)
                specs.append(jax.ShapeDtypeStruct(shape, jnp.dtype(
                    s.dtype if isinstance(s.dtype, str) else s.dtype)))
            elif isinstance(s, Tensor):
                specs.append(jax.ShapeDtypeStruct(tuple(s._data.shape), s._data.dtype))
        layer.eval()

        def fwd(params, *inputs):
            st = {n: Tensor(p) for n, p in zip(names, params)}
            old = {n: state[n]._data for n in names}
            for n in names:
                state[n]._data = st[n]._data
            try:
                out = layer(*[Tensor(i) for i in inputs])
            finally:
                for n in names:
                    state[n]._data = old[n]
            return jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, Tensor) else x, out,
                is_leaf=lambda x: isinstance(x, Tensor))

        try:
            from jax import export as jax_export
            param_specs = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in param_arrays]
            exp = jax_export.export(jax.jit(fwd))(param_specs, *specs)
            exported_bytes = exp.serialize()
        except Exception:
            exported_bytes = None  # fall back to pickle-only (re-trace on load)

    input_names, input_specs, output_names = [], [], ["out0"]
    if input_spec:
        for i, s in enumerate(input_spec):
            if isinstance(s, InputSpec):
                input_names.append(s.name or f"x{i}")
                input_specs.append((tuple(s.shape), str(s.dtype)))
            elif isinstance(s, Tensor):
                input_names.append(getattr(s, "name", None) or f"x{i}")
                input_specs.append((tuple(s._data.shape), str(s._data.dtype)))
    if exported_bytes is not None:
        try:
            output_names = [f"out{i}" for i in range(len(exp.out_avals))]
        except Exception:
            pass  # exported object lacks out_avals (older jax_export):
            #       artifact ships without output names, loaders tolerate it

    from ..framework.artifact import write_artifact
    write_artifact(path + ".pdmodel", {
        "format": "paddle_tpu.jit.v2",
        "state_names": names,
        "class_name": type(layer).__name__,
        "input_names": input_names,
        "input_specs": input_specs,
        "output_names": output_names,
    }, blobs=({"stablehlo": exported_bytes}
              if exported_bytes is not None else {}),
        arrays={f"state/{i}": np.asarray(a)
                for i, a in enumerate(param_arrays)})
    # params also in paddle.save format for cross-loading
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(_pack(dict(state)), f, protocol=4)


class TranslatedLayer:
    """Executable loaded program (parity: paddle.jit.TranslatedLayer)."""

    def __init__(self, payload):
        self._names = payload["state_names"]
        self._params = [jnp.asarray(a) for a in payload["state"]]
        self._exported = None
        if payload.get("stablehlo"):
            from jax import export as jax_export
            self._exported = jax_export.deserialize(payload["stablehlo"])

    def __call__(self, *inputs):
        if self._exported is None:
            raise RuntimeError(
                "this artifact was saved without input_spec, so no compiled "
                "program is embedded; reload the original Layer and state via "
                "paddle.load instead")
        arrs = [i._data if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs]
        out = self._exported.call(self._params, *arrs)
        return jax.tree_util.tree_map(lambda x: Tensor(x), out)

    forward = __call__

    def state_dict(self):
        return {n: Tensor(p) for n, p in zip(self._names, self._params)}

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")


def load(path: str, **configs) -> TranslatedLayer:
    from ..framework.artifact import read_model_payload
    return TranslatedLayer(read_model_payload(path + ".pdmodel"))
