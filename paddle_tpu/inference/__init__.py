"""Inference API: ``paddle.inference`` — Config / create_predictor / Predictor.

Parity surface: the reference's AnalysisPredictor stack
(paddle/fluid/inference/api/ — paddle_infer::Config, CreatePredictor,
zero-copy input/output handles; see SURVEY.md §3.5).

TPU-native design: the "analysis + IR passes + executor build" phase of the
reference collapses into XLA — the artifact produced by ``paddle.jit.save``
or ``paddle.static.save_inference_model`` already holds a serialized
StableHLO module; the Predictor deserializes it, AOT-compiles once per input
signature, and runs with zero host round-trips between ops. Handles mimic
the zero-copy Tensor API (copy_from_cpu / copy_to_cpu).
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .. import observability as _obs

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class Config:
    """Parity: paddle_infer::Config. GPU/TRT/MKLDNN toggles are accepted and
    recorded but are no-ops on TPU (XLA owns optimization); documented
    divergence."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # accepted forms: Config(prefix), Config(prefix.pdmodel, prefix.pdiparams)
        if prog_file and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._precision = PrecisionType.Float32
        self._device = "tpu"
        self._flags: Dict[str, Any] = {}

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._prefix = (prog_file[:-len(".pdmodel")]
                        if prog_file.endswith(".pdmodel") else prog_file)

    def model_dir(self):
        return self._prefix

    # -- accepted no-op toggles (recorded for parity) ----------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator is the TPU on this stack

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, *a, **k):
        self._flags["memory_optim"] = True

    def switch_ir_optim(self, flag=True):
        self._flags["ir_optim"] = flag

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    def enable_tensorrt_engine(self, *a, **k):
        self._flags["trt"] = False  # no TRT on TPU; XLA already fuses

    def use_ortt(self, *a, **k):  # pragma: no cover - exotic parity stub
        pass

    def precision(self):
        return self._precision


class _IOHandle:
    """Zero-copy-style tensor handle (parity: paddle_infer::Tensor)."""

    def __init__(self, name: str):
        self.name = name
        self._array: Optional[np.ndarray] = None

    def reshape(self, shape):
        if self._array is None:
            self._array = np.zeros(shape, np.float32)
        else:
            self._array = self._array.reshape(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._array = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._array)

    def shape(self):
        return list(self._array.shape) if self._array is not None else []


class Predictor:
    """Executes a saved inference artifact (jit.save or
    save_inference_model output)."""

    def __init__(self, config: Config):
        prefix = config._prefix
        if prefix is None:
            raise ValueError("Config has no model path")
        path = prefix + ".pdmodel"
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        from ..framework.artifact import read_model_payload
        payload = read_model_payload(path)
        fmt = payload.get("format", "")
        from jax import export as jax_export

        if fmt == "paddle_tpu.static_inference.v2":
            self._exported = jax_export.deserialize(payload["stablehlo"])
            self._input_names = list(payload["feed_names"])
            self._output_names = list(payload["fetch_names"])
            self._params = None
        elif fmt == "paddle_tpu.jit.v2":
            if not payload.get("stablehlo"):
                raise RuntimeError(
                    "artifact was saved without input_spec; re-save with "
                    "paddle.jit.save(layer, path, input_spec=[...])")
            self._exported = jax_export.deserialize(payload["stablehlo"])
            import jax.numpy as jnp
            self._params = [jnp.asarray(a) for a in payload["state"]]
            self._input_names = list(payload.get(
                "input_names",
                [f"x{i}" for i in range(self._n_data_inputs(payload))]))
            self._output_names = list(payload.get("output_names", ["out0"]))
        else:
            raise ValueError(f"unknown inference artifact format: {fmt!r}")
        self._inputs = {n: _IOHandle(n) for n in self._input_names}
        self._outputs = {n: _IOHandle(n) for n in self._output_names}
        # the serving layer (and any multi-threaded server) calls run()
        # concurrently on one Predictor; the compiled call itself is pure,
        # but the handle read/writes around it are not — serialize them
        self._run_lock = threading.Lock()

    @staticmethod
    def _n_data_inputs(payload) -> int:
        return len(payload.get("input_specs", [])) or 1

    # -- paddle_infer::Predictor surface -----------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return self._inputs[name]

    def get_output_handle(self, name: str) -> _IOHandle:
        return self._outputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Either positional-list style ``run([arr, ...]) -> [arr, ...]`` or
        handle style (copy_from_cpu … run() … copy_to_cpu). run() bodies
        serialize on a per-predictor lock, so POSITIONAL-LIST calls are
        fully thread-safe (each returns its own outputs). Handle-style use
        spans the lock (write handles, run, read handles are three calls):
        concurrent handle-style callers must coordinate externally or use
        the positional form."""
        import jax.numpy as jnp

        with self._run_lock:
            if inputs is not None:
                arrs = [jnp.asarray(a) for a in inputs]
            else:
                arrs = [jnp.asarray(self._inputs[n].copy_to_cpu())
                        for n in self._input_names]
            if self._params is not None:
                outs = self._exported.call(self._params, *arrs)
            else:
                outs = self._exported.call(*arrs)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            outs_np = [np.asarray(o) for o in outs]
            for n, o in zip(self._output_names, outs_np):
                self._outputs[n]._array = o
        _obs.inc("inference.runs_total")
        return outs_np if inputs is not None else None


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
