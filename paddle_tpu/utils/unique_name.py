"""``paddle.utils.unique_name`` (reference:
python/paddle/base/unique_name.py): process-wide name generator with
guard-scoped prefixes."""

from __future__ import annotations

import contextlib
from collections import defaultdict
from typing import Dict

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids: Dict[str, int] = defaultdict(int)
        self.prefix = ""

    def gen(self, key: str) -> str:
        i = self.ids[key]
        self.ids[key] += 1
        return f"{self.prefix}{key}_{i}"


_generator = _Generator()


def generate(key: str) -> str:
    return _generator.gen(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else _Generator()
    return old


@contextlib.contextmanager
def guard(new_prefix: str = ""):
    """Fresh name scope (reference semantics: names restart inside)."""
    old = switch()
    _generator.prefix = new_prefix or ""
    try:
        yield
    finally:
        switch(old)
