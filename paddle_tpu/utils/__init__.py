"""``paddle.utils`` (reference: python/paddle/utils/ — download, deprecated,
unique_name, try_import, run_check, cpp_extension).

TPU build notes: ``download`` is gated (this environment is zero-egress, and
the framework ships no pretrained-weight mirror); ``cpp_extension`` builds
C++ via setuptools/ctypes rather than pybind11 (not vendored here).
"""

from __future__ import annotations

import functools
import importlib
import warnings

from . import cpp_extension  # noqa: F401
from . import dlpack  # noqa: F401
from . import download  # noqa: F401
from . import profiler  # noqa: F401
from . import unique_name  # noqa: F401
from .lazy_import import try_import  # noqa: F401

__all__ = ["deprecated", "try_import", "run_check", "unique_name",
           "require_version", "dlpack", "download", "cpp_extension",
           "profiler"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Decorator marking an API deprecated (parity:
    python/paddle/utils/deprecated.py)."""

    def decorator(fn):
        msg = f"API '{fn.__qualname__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"
        if level == 2:
            @functools.wraps(fn)
            def raising(*a, **k):
                raise RuntimeError(msg)
            return raising

        @functools.wraps(fn)
        def wrapper(*a, **k):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **k)
        wrapper.__doc__ = (fn.__doc__ or "") + f"\n\n.. deprecated:: {msg}"
        return wrapper

    return decorator


def require_version(min_version: str, max_version: str | None = None) -> bool:
    from .. import __version__
    def parse(v):
        return tuple(int(x) for x in v.split(".")[:3] if x.isdigit())
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True


def run_check() -> None:
    """Sanity-check the install (parity: paddle.utils.run_check): one matmul
    on the default device, plus a multi-device mesh check when available."""
    import jax
    import numpy as np

    from .. import to_tensor, matmul

    a = to_tensor(np.ones((16, 16), np.float32))
    out = matmul(a, a)
    assert float(out._data[0, 0]) == 16.0
    from .. import device as _device
    ndev = len(_device.get_all_devices())
    print(f"PaddleTPU works well on 1 {jax.default_backend()} device.")
    if ndev > 1:
        print(f"PaddleTPU is installed successfully across {ndev} devices!")
    else:
        print("PaddleTPU is installed successfully!")


