"""try_import (reference: python/paddle/utils/lazy_import.py)."""

from __future__ import annotations

import importlib

__all__ = ["try_import"]


def try_import(module_name: str, err_msg: str | None = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Optional dependency {module_name!r} is required for "
            "this feature; it is not installed in this environment.")
