"""``paddle.utils.download`` (reference: python/paddle/utils/download.py).

Zero-egress build: remote fetches are gated. Local files and pre-populated
cache dirs work; a URL whose mapped cache file already exists resolves to it.
"""

from __future__ import annotations

import hashlib
import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.environ.get(
    "PADDLE_WEIGHTS_HOME", osp.expanduser("~/.cache/paddle_tpu/weights"))


def _md5check(fullname: str, md5sum: str | None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str = WEIGHTS_HOME,
                      md5sum: str | None = None, check_exist: bool = True):
    """Resolve a URL to a local cache path; never fetches (zero egress)."""
    if osp.exists(url):  # already a local path
        return url
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if osp.exists(fullname) and _md5check(fullname, md5sum):
        return fullname
    raise RuntimeError(
        f"cannot download {url!r}: this build runs zero-egress. Place the "
        f"file at {fullname!r} (or pass a local path) and retry.")


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
