"""``paddle.utils.dlpack`` — zero-copy tensor exchange via the DLPack
protocol (reference: python/paddle/utils/dlpack.py). jax arrays implement
``__dlpack__`` natively, so this is a thin seam."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x: Tensor):
    """Export a Tensor as a DLPack capsule. Zero-copy from the jax buffer
    when the PJRT backend supports external references; otherwise stages
    through host memory (relay-attached TPUs)."""
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    try:
        return arr.__dlpack__()
    except Exception:
        import numpy as np
        return np.array(jax.device_get(arr)).__dlpack__()  # writable host copy


class _CapsuleHolder:
    """Adapter giving a raw DLPack capsule the array-API protocol surface
    (consumers now expect ``__dlpack__``/``__dlpack_device__``, not bare
    capsules). Host capsules only — device is kDLCPU."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # (kDLCPU, 0)


def from_dlpack(capsule) -> Tensor:
    """Import a DLPack capsule (or any object with ``__dlpack__``)."""
    if hasattr(capsule, "__dlpack__"):
        return to_tensor(jnp.from_dlpack(capsule))
    return to_tensor(jnp.from_dlpack(_CapsuleHolder(capsule)))
