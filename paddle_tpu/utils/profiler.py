"""``paddle.utils.profiler`` — legacy profiler entry points (reference:
python/paddle/utils/profiler.py), forwarding to the modern
``paddle.profiler`` package."""

from __future__ import annotations

import logging

from ..profiler import Profiler, ProfilerTarget, RecordEvent  # noqa: F401

_active: Profiler | None = None


def start_profiler(state: str = "All", tracer_option: str = "Default") -> None:
    global _active
    if _active is None:
        _active = Profiler()
        _active.start()


def stop_profiler(sorted_key: str = "total",
                  profile_path: str = "/tmp/profile") -> None:
    global _active
    if _active is not None:
        _active.stop()
        try:
            _active.export_chrome_tracing(profile_path)
        except Exception:
            # the session still stopped cleanly; losing the export file is
            # worth a line, not a crash of the training run being profiled
            logging.getLogger(__name__).warning(
                "chrome trace export to %s failed", profile_path,
                exc_info=True)
        _active = None


class profiler:
    """Context-manager parity for ``with paddle.utils.profiler.profiler(...)``."""

    def __init__(self, state: str = "All", sorted_key: str = "total",
                 profile_path: str = "/tmp/profile"):
        self.profile_path = profile_path

    def __enter__(self):
        start_profiler()
        return self

    def __exit__(self, *exc):
        stop_profiler(profile_path=self.profile_path)
        return False
