"""Scalar/metric sink with the VisualDL ``LogWriter`` surface.

Parity: VisualDL's ``LogWriter`` (the scalar sink upstream hapi callbacks
and user code write to; VisualDL itself is a separate package). TPU-native
design: records land in two interchangeable formats —

* a JSONL event stream (``vdlrecords.<ts>.jsonl``) that is trivially
  greppable/plottable and safe to append from long jobs;
* optionally TensorBoard event files via ``jax.profiler`` infrastructure's
  sibling, ``tensorboardX``-style protos, when ``tensorboard`` is
  importable (it is not in the baked image — the JSONL stream is the
  format of record).

Usage (VisualDL-compatible)::

    from paddle_tpu.utils.logwriter import LogWriter
    with LogWriter(logdir="./log") as w:
        w.add_scalar(tag="train/loss", value=float(loss), step=i)
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["LogWriter"]


class LogWriter:
    def __init__(self, logdir: str = "./log", max_queue: int = 1024,
                 flush_secs: int = 10, file_name: str = "", **kwargs):
        os.makedirs(logdir, exist_ok=True)
        self.logdir = logdir
        name = file_name or f"vdlrecords.{int(time.time())}.jsonl"
        self._path = os.path.join(logdir, name)
        self._f = open(self._path, "a")  # block-buffered; the
        # flush_secs timer below bounds staleness
        self._lock = threading.Lock()
        self._flush_secs = flush_secs
        self._last_flush = time.monotonic()

    # -- record types --------------------------------------------------------
    def add_scalar(self, tag: str, value, step: Optional[int] = None,
                   walltime: Optional[float] = None) -> None:
        self._write({"type": "scalar", "tag": tag, "value": float(value),
                     "step": int(step or 0),
                     "walltime": walltime or time.time()})

    def add_scalars(self, main_tag: str, tag_scalar_dict: Dict[str, Any],
                    step: Optional[int] = None) -> None:
        for k, v in tag_scalar_dict.items():
            self.add_scalar(f"{main_tag}/{k}", v, step)

    def add_text(self, tag: str, text_string: str,
                 step: Optional[int] = None) -> None:
        self._write({"type": "text", "tag": tag, "value": str(text_string),
                     "step": int(step or 0), "walltime": time.time()})

    def add_hparams(self, hparams_dict: Dict[str, Any],
                    metrics_list=None, **kw) -> None:
        self._write({"type": "hparams", "value": dict(hparams_dict),
                     "metrics": list(metrics_list or []),
                     "walltime": time.time()})

    def add_histogram(self, tag: str, values, step: Optional[int] = None,
                      buckets: int = 10) -> None:
        import numpy as np

        arr = np.asarray(values, np.float64).ravel()
        counts, edges = np.histogram(arr, bins=buckets)
        self._write({"type": "histogram", "tag": tag,
                     "counts": counts.tolist(), "edges": edges.tolist(),
                     "step": int(step or 0), "walltime": time.time()})

    # -- plumbing ------------------------------------------------------------
    def _write(self, rec: dict) -> None:
        with self._lock:
            self._f.write(json.dumps(rec) + "\n")
            now = time.monotonic()
            if now - self._last_flush >= self._flush_secs:
                self._f.flush()
                self._last_flush = now

    def flush(self) -> None:
        with self._lock:
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def file_name(self) -> str:
        return self._path
