"""``paddle.utils.cpp_extension`` — build/load C++ extensions at runtime
(reference: python/paddle/utils/cpp_extension/).

TPU-native shape: extensions are host-side C++ (custom data loaders, RPC,
CPU ops) compiled with the system toolchain and bound via ctypes — the
same seam the in-tree native runtime uses (paddle_tpu/_native). CUDA
sources are rejected: device code on TPU is written in Pallas, not C++.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig as _pysysconfig
import tempfile

__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension",
           "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(), "paddle_tpu_ext"))
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         verbose: bool = False):
    """Compile C++ sources to a shared object and load it via ctypes.

    Returns the loaded ``ctypes.CDLL``; exported ``extern "C"`` symbols are
    callable directly. (The reference returns a python module of custom ops;
    here custom *device* ops are Pallas kernels registered in python, so the
    C++ seam is host-runtime only.)
    """
    if extra_cuda_cflags:
        raise RuntimeError("CUDA sources are not supported on the TPU build; "
                           "write device kernels in Pallas instead.")
    build_dir = build_directory or get_build_directory()
    out = os.path.join(build_dir, f"{name}.so")
    srcs = [os.path.abspath(s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not (os.path.exists(out) and os.path.getmtime(out) >= newest_src):
        cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
               + (extra_cxx_cflags or [])
               + [f"-I{p}" for p in (extra_include_paths or [])]
               + [f"-I{_pysysconfig.get_paths()['include']}"]
               + srcs + ["-o", out] + (extra_ldflags or []))
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)


class CppExtension:
    """setuptools-style extension spec (parity shim)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise RuntimeError("CUDAExtension is not available on the TPU build; "
                       "device kernels are Pallas (see ops/flash_attention.py).")


class BuildExtension:
    """Parity shim for setup(cmdclass={'build_ext': BuildExtension})."""

    @classmethod
    def with_options(cls, **_):
        return cls
