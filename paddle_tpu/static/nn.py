"""``paddle.static.nn`` — control-flow ops (+ thin layer aliases).

Parity surface: python/paddle/static/nn/control_flow.py (``while_loop``,
``cond``, ``case``, ``switch_case``; the reference lowers these to the legacy
``while_op`` / ``conditional_block_op`` C++ operators —
paddle/fluid/operators/controlflow/).

TPU-native design: structured control flow maps 1:1 onto XLA's control-flow
HLOs via ``lax.while_loop`` / ``lax.cond`` / ``lax.switch`` — traced once,
compiled, no Python in the loop body at run time. When the predicate is a
concrete Python/host value (pure eager, nothing traced) the branch is taken
directly, mirroring the reference's eager fast path.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core import tracing as _tracing
from ..core.tensor import Tensor
from ..core.tracing import no_grad

__all__ = ["while_loop", "cond", "case", "switch_case"]


def _flatten(vars):  # Tensors are leaves; keep exact container shape
    return jax.tree_util.tree_flatten(
        vars, is_leaf=lambda x: isinstance(x, Tensor))


def _as_array(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _rewrap(leaves, template_leaves, treedef):
    out = [Tensor(d) if isinstance(t, Tensor) else d
           for d, t in zip(leaves, template_leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: str | None = None) -> List:
    """Run ``body`` while ``cond`` holds. ``cond``/``body`` take ``*loop_vars``
    and ``body`` returns the next loop_vars (same structure & shapes — XLA's
    fixed-shape loop-carried-state rule, identical to the reference's
    requirement that while_op block outputs match inputs)."""
    leaves, treedef = _flatten(list(loop_vars))
    datas = [_as_array(l) for l in leaves]

    needs_grad = (_tracing.grad_enabled() and
                  any(isinstance(l, Tensor) and not l.stop_gradient
                      for l in leaves))
    if needs_grad and not any(_is_traced(d) for d in datas):
        # differentiable eager path: unroll through the tape (the analogue of
        # the reference while_op recording per-iteration blocks for backward)
        vars_ = list(loop_vars)
        while bool(_as_array(cond(*vars_))):
            r = body(*vars_)
            vars_ = list(r) if isinstance(r, (tuple, list)) else [r]
        return vars_
    if needs_grad:
        raise RuntimeError(
            "while_loop with differentiable loop_vars inside to_static is not "
            "supported (XLA's while is not reverse-differentiable); mark the "
            "loop_vars stop_gradient, wrap the loop in paddle.no_grad(), or "
            "use a bounded-trip-count formulation")

    def c(ds):
        r = cond(*_rewrap(ds, leaves, treedef))
        return _as_array(r).reshape(())

    def b(ds):
        r = body(*_rewrap(ds, leaves, treedef))
        if not isinstance(r, (tuple, list)):
            r = [r]
        new_leaves, _ = _flatten(list(r))
        return [_as_array(l) for l in new_leaves]

    with no_grad():
        final = jax.lax.while_loop(c, b, datas)
    return list(_rewrap(final, leaves, treedef))


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _make_branch(fn, info):
    """Wrap a user branch fn so it runs INSIDE the lax combinator's trace,
    returning flat arrays; the output structure is captured on first trace."""
    def branch(_):
        with no_grad():
            out = fn() if fn is not None else None
        leaves, treedef = _flatten(out)
        info.setdefault("leaves", leaves)
        info.setdefault("treedef", treedef)
        return [_as_array(l) for l in leaves]
    return branch


def cond(pred, true_fn: Callable = None, false_fn: Callable = None,
         name: str | None = None):
    """Two-way branch (parity: paddle.static.nn.cond). Both branches must
    return matching structures/shapes; lowers to ``lax.cond`` so only the
    taken branch executes on device."""
    parr = _as_array(pred)
    if not _is_traced(parr):  # concrete: eager fast path
        taken = true_fn if bool(parr) else false_fn
        return taken() if taken is not None else None

    if _tracing.grad_enabled():
        # differentiable path: evaluate BOTH branches through the tape and
        # select elementwise — where()'s vjp masks the untaken side, so
        # gradients flow exactly like the reference's conditional_block_grad.
        # (lax.cond would run the branches detached; select trades the
        # run-one-branch saving for autograd support, the right default in a
        # training graph.)
        from ..ops import indexing as _ops
        t_out = true_fn() if true_fn is not None else None
        f_out = false_fn() if false_fn is not None else None
        t_leaves, t_def = _flatten(t_out)
        f_leaves, _ = _flatten(f_out)
        pbool = parr.reshape(()).astype(bool)
        sel = []
        for a, b in zip(t_leaves, f_leaves):
            if isinstance(a, Tensor) or isinstance(b, Tensor):
                at = a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
                bt = b if isinstance(b, Tensor) else Tensor(jnp.asarray(b))
                sel.append(_ops.where(Tensor(
                    jnp.broadcast_to(pbool, at._data.shape)), at, bt))
            else:
                sel.append(jnp.where(pbool, jnp.asarray(a), jnp.asarray(b)))
        return jax.tree_util.tree_unflatten(t_def, sel)

    info: dict = {}
    out = jax.lax.cond(parr.reshape(()).astype(bool),
                       _make_branch(true_fn, info),
                       _make_branch(false_fn, info), 0)
    return _rewrap(out, info["leaves"], info["treedef"])


def case(pred_fn_pairs: Sequence[Tuple[Any, Callable]],
         default: Callable = None, name: str | None = None):
    """First-match-wins chain of (pred, fn) (parity: paddle.static.nn.case),
    built as nested ``cond``s."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")

    def build(i):
        if i == len(pred_fn_pairs):
            if default is None:
                return pred_fn_pairs[-1][1]  # reference semantics: last fn
            return default
        pred, fn = pred_fn_pairs[i]
        return lambda: cond(pred, fn, build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default: Callable = None,
                name: str | None = None):
    """Index-selected branch (parity: paddle.static.nn.switch_case); lowers to
    ``lax.switch``. ``branch_fns`` is a dict {int: fn} or list of fns/pairs."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]

    idx_arr = _as_array(branch_index).reshape(()).astype(jnp.int32)
    if not _is_traced(idx_arr):
        return dict(items).get(int(idx_arr), default or fns[-1])()

    # remap the (possibly sparse) keys to dense switch positions; with no
    # explicit default the no-match case reuses the LAST branch's slot
    # (reference semantics) instead of tracing it twice
    keys_arr = jnp.asarray(keys, jnp.int32)
    hit = idx_arr == keys_arr
    miss_slot = len(fns) if default is not None else len(fns) - 1
    sel = jnp.where(hit.any(), jnp.argmax(hit), miss_slot).astype(jnp.int32)
    table = fns + ([default] if default is not None else [])

    if _tracing.grad_enabled():
        # differentiable: run every branch on the tape, fold with where()
        # (see cond() — same select-for-autograd tradeoff)
        from ..ops import indexing as _ops
        branch_outs = [f() for f in table]
        acc_leaves, treedef = _flatten(branch_outs[-1])
        acc = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
               for a in acc_leaves]
        for i in range(len(fns)):
            bl, _ = _flatten(branch_outs[i])
            m = sel == i
            acc = [_ops.where(Tensor(jnp.broadcast_to(m, a._data.shape)),
                              b if isinstance(b, Tensor)
                              else Tensor(jnp.asarray(b)), a)
                   for a, b in zip(acc, bl)]
        return jax.tree_util.tree_unflatten(treedef, acc)

    info: dict = {}
    out = jax.lax.switch(sel, [_make_branch(f, info) for f in table], 0)
    return _rewrap(out, info["leaves"], info["treedef"])


# ---------------------------------------------------------------------------
# Layer-building ops (reference: python/paddle/static/nn/common.py — fc,
# conv2d, batch_norm…): declarative layers that create their parameters at
# call time inside a Program.
# ---------------------------------------------------------------------------

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from .. import nn
    from . import create_parameter
    from ..core.tensor import apply
    import jax.numpy as jnp

    def _one(t):
        shp = tuple(t._data.shape)
        in_dim = 1
        for s in shp[num_flatten_dims:]:
            in_dim *= int(s)
        w = create_parameter([in_dim, size], t._data.dtype, attr=weight_attr)
        # flatten relative to the runtime array — leading (batch) dims must
        # not be baked in from the capture-time placeholder
        out = apply("fc",
                    lambda a, wt: a.reshape(a.shape[:num_flatten_dims] + (-1,)) @ wt,
                    t, w)
        return out

    xs = x if isinstance(x, (list, tuple)) else [x]
    out = _one(xs[0])
    for t in xs[1:]:
        out = out + _one(t)
    if bias_attr is not False:
        b = create_parameter([size], out._data.dtype, attr=None
                             if bias_attr in (None, True) else bias_attr,
                             is_bias=True)
        out = out + b
    if activation:
        out = getattr(nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    from .. import nn
    from . import create_parameter

    ksize = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    in_ch = int(input._data.shape[1 if data_format == "NCHW" else -1])
    w = create_parameter([num_filters, in_ch // groups, *ksize],
                         input._data.dtype, attr=param_attr)
    b = None
    if bias_attr is not False:
        b = create_parameter([num_filters], input._data.dtype, is_bias=True,
                             attr=None if bias_attr is True else bias_attr)
    out = nn.functional.conv2d(input, w, bias=b, stride=stride,
                               padding=padding, dilation=dilation,
                               groups=groups, data_format=data_format)
    if act:
        out = getattr(nn.functional, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False):
    from .. import nn
    from . import create_parameter, create_global_var
    from ..nn import initializer as I

    ch = int(input._data.shape[1 if data_layout == "NCHW" else -1])
    dt = input._data.dtype
    scale = create_parameter([ch], dt, attr=param_attr,
                             default_initializer=I.Constant(1.0))
    bias = create_parameter([ch], dt, is_bias=True, attr=bias_attr)
    mean = create_global_var([ch], 0.0, dt, persistable=True,
                             name=moving_mean_name)
    var = create_global_var([ch], 1.0, dt, persistable=True,
                            name=moving_variance_name)
    out = nn.functional.batch_norm(input, mean, var, weight=scale, bias=bias,
                                   training=not is_test, momentum=momentum,
                                   epsilon=epsilon, data_format=data_layout,
                                   use_global_stats=use_global_stats)
    if act:
        out = getattr(nn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from .. import nn
    from . import create_parameter
    from ..core.dtype import convert_dtype
    from ..nn import initializer as I

    w = create_parameter(list(size), convert_dtype(dtype), attr=param_attr,
                         default_initializer=I.XavierNormal())
    return nn.functional.embedding(input, w, padding_idx=padding_idx,
                                   sparse=is_sparse)


__all__ += ["fc", "conv2d", "batch_norm", "embedding"]
