"""Static graph façade: define-once, run-many programs.

Parity surface: ``paddle.static`` (reference: python/paddle/static/ — Program
/ Executor / data / program_guard / save & load_inference_model; the C++
strata behind it are ProgramDesc + StandaloneExecutor in
paddle/fluid/framework/, see SURVEY.md §2.2).

TPU-native design — *record/replay over the eager dispatch seam*, not a
ProgramDesc interpreter:

- While static mode captures, every op dispatched through
  ``core.tensor.apply`` is appended to the current ``Program`` as a node
  ``(op_name, pure_jax_fn, input_tensors, output_tensors)``. The pure fn is
  exactly the kernel closure XLA will compile — the Program IS a jaxpr-able
  op list (the PIR analogue), with Parameters appearing as captured leaves.
- ``Executor.run`` replays the op list with feeds substituted, wrapped in
  ``paddle.jit.to_static`` so the whole program compiles to ONE XLA
  executable (the StandaloneExecutor's instruction-stream role collapses
  into XLA's scheduler). ``optimizer.minimize(loss)`` captured in the
  program makes ``run`` a full compiled train step: replay → backward →
  update (the generated backward ops of the reference's append_backward).
- Feeds with new shapes simply re-trace (static shapes per executable —
  XLA's compilation model).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import tensor as _tensor_mod
from ..core.tensor import Tensor

__all__ = [
    "Program", "Executor", "data", "program_guard", "default_main_program",
    "default_startup_program", "enable_static", "disable_static",
    "in_static_mode", "save_inference_model", "load_inference_model",
    "InputSpec", "global_scope", "CompiledProgram",
]

from ..jit.save_load import InputSpec  # re-export (paddle.static.InputSpec)


class Program:
    """A recorded op graph. ``clone(for_test=True)`` returns a view without
    the minimize step (parity: Program.clone)."""

    def __init__(self):
        self._records: List[Tuple[str, Any, Tuple[Tensor, ...],
                                  Tuple[Tensor, ...]]] = []
        self._feeds: Dict[str, Tensor] = {}
        self._minimize: Optional[Tuple[Any, Tensor]] = None  # (optimizer, loss)
        self._exec_cache: Dict[Any, Any] = {}
        self.random_seed = 0

    def record(self, op_name, fn, inputs, outputs):
        self._records.append((op_name, fn, tuple(inputs), tuple(outputs)))
        self._exec_cache.clear()

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p._records = list(self._records)
        p._feeds = dict(self._feeds)
        p._minimize = None if for_test else self._minimize
        return p

    def list_vars(self):
        seen, out = set(), []
        for _, _, ins, outs in self._records:
            for t in (*ins, *outs):
                if id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    def global_block(self):
        return self  # parity shim: block-level APIs resolve on the program

    def __repr__(self):
        return (f"Program({len(self._records)} ops, "
                f"feeds={list(self._feeds)}, "
                f"minimize={'yes' if self._minimize else 'no'})")


_default_main = Program()
_default_startup = Program()
_static_mode = False


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


def _current_program() -> Program:
    return _default_main


def _record_hook(op_name, fn, tensor_inputs, out_tensors):
    _current_program().record(op_name, fn, tensor_inputs, out_tensors)


def enable_static() -> None:
    """Enter static capture mode: ops now record into the default main
    program (fresh). paddle.enable_static() parity."""
    global _static_mode, _default_main, _default_startup
    _static_mode = True
    _default_main = Program()
    _default_startup = Program()
    _tensor_mod._op_graph_hook = _record_hook


def disable_static() -> None:
    global _static_mode
    _static_mode = False
    _tensor_mod._op_graph_hook = None


def in_static_mode() -> bool:
    return _static_mode


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    global _default_main, _default_startup
    old_main, old_startup = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = old_main, old_startup


def data(name: str, shape: Sequence[Optional[int]], dtype: str = "float32",
         lod_level: int = 0) -> Tensor:
    """Declare a feed placeholder. The placeholder carries a concrete
    zero array (dim None/-1 → 1) purely to drive capture; Executor.run
    re-traces per actual feed shape."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype

    declared = tuple(None if (s is None or int(s) < 0) else int(s)
                     for s in shape)
    concrete = tuple(1 if s is None else s for s in declared)
    t = Tensor(jnp.zeros(concrete, dtype=convert_dtype(dtype)),
               stop_gradient=True)
    t.name = name
    t._declared_shape = declared  # None dims stay symbolic for export
    _current_program()._feeds[name] = t
    return t


class _Scope:
    def find_var(self, name):
        for prog in (_default_main, _default_startup):
            for t in prog.list_vars():
                if getattr(t, "name", None) == name:
                    return t
        return None


_scope = _Scope()


def global_scope() -> _Scope:
    return _scope


class CompiledProgram:
    """Parity shim: compilation happens in Executor.run (whole-program jit);
    CompiledProgram(prog) just forwards."""

    def __init__(self, program: Program, build_strategy=None):
        self.program = program


class Executor:
    """Runs a Program: one whole-program XLA executable per feed signature."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[List] = None, return_numpy: bool = True):
        if isinstance(program, CompiledProgram):
            program = program.program
        if isinstance(program, _LoadedProgram):
            return program._run(feed or {}, return_numpy)
        program = program if program is not None else _default_main
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program._records:  # startup program: params already init'ed
            return []

        # Re-execution happens eagerly through `apply` (so minimize's
        # backward works), wrapped in to_static for whole-program XLA
        # compilation; static capture must be suspended during replay.
        feed_names = tuple(sorted(feed))
        key = (feed_names, tuple(id(f) for f in fetch_list))
        runner = program._exec_cache.get(key)
        if runner is None:
            runner = self._build_runner(program, feed_names, fetch_list)
            program._exec_cache[key] = runner
        feed_tensors = [self._to_tensor(feed[n]) for n in feed_names]
        outs = runner(*feed_tensors)
        if return_numpy:
            return [np.asarray(o.numpy()) for o in outs]
        return list(outs)

    @staticmethod
    def _to_tensor(x) -> Tensor:
        if isinstance(x, Tensor):
            return x
        import jax.numpy as jnp
        return Tensor(jnp.asarray(x))

    def _build_runner(self, program: Program, feed_names, fetch_list):
        from ..jit.to_static import to_static
        from ..core.tensor import apply

        def _replay(*feed_tensors):
            hook = _tensor_mod._op_graph_hook
            _tensor_mod._op_graph_hook = None  # no capture while replaying
            try:
                env: Dict[int, Tensor] = {}
                for name, ft in zip(feed_names, feed_tensors):
                    ph = program._feeds.get(name)
                    if ph is not None:
                        env[id(ph)] = ft
                for op_name, fn, ins, outs in program._records:
                    new_ins = [env.get(id(t), t) for t in ins]
                    res = apply(op_name, fn, *new_ins, amp=False)
                    res_t = res if isinstance(res, tuple) else (res,)
                    for o, r in zip(outs, res_t):
                        env[id(o)] = r
                if program._minimize is not None:
                    opt, loss = program._minimize
                    new_loss = env.get(id(loss), loss)
                    new_loss.backward()
                    opt.step()
                    opt.clear_grad()
                return tuple(env.get(id(f), f) for f in fetch_list)
            finally:
                _tensor_mod._op_graph_hook = hook

        return to_static(_replay)


# ---------------------------------------------------------------------------
# Inference model save/load (reference: paddle.static.save_inference_model →
# serialized program + params; here: StableHLO via jax.export, params
# embedded as XLA constants)
# ---------------------------------------------------------------------------

def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program: Optional[Program] = None, **configs) -> None:
    import os
    import pickle
    import jax
    import jax.numpy as jnp
    from ..core.tensor import apply

    program = program or _default_main
    feed_vars = list(feed_vars) if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = list(fetch_vars) if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    inference = program.clone(for_test=True)

    def fwd(*feed_arrays):
        env: Dict[int, Any] = {id(v): Tensor(a)
                               for v, a in zip(feed_vars, feed_arrays)}
        hook = _tensor_mod._op_graph_hook
        _tensor_mod._op_graph_hook = None
        try:
            from ..core.tracing import no_grad
            with no_grad():
                for op_name, fn, ins, outs in inference._records:
                    new_ins = [env.get(id(t), t) for t in ins]
                    res = apply(op_name, fn, *new_ins, amp=False)
                    res_t = res if isinstance(res, tuple) else (res,)
                    for o, r in zip(outs, res_t):
                        env[id(o)] = r
        finally:
            _tensor_mod._op_graph_hook = hook
        return tuple(env.get(id(f), f)._data for f in fetch_vars)

    from jax import export as jax_export

    def _specs(polymorphic: bool):
        specs = []
        for i, v in enumerate(feed_vars):
            decl = getattr(v, "_declared_shape", None)
            if polymorphic and decl and any(s is None for s in decl):
                dims = ", ".join(f"d{i}_{j}" if s is None else str(s)
                                 for j, s in enumerate(decl))
                shape = jax_export.symbolic_shape(dims)
                specs.append(jax.ShapeDtypeStruct(shape, v._data.dtype))
            else:
                specs.append(jax.ShapeDtypeStruct(tuple(v._data.shape),
                                                  v._data.dtype))
        return specs

    try:
        # shape-polymorphic export: None dims in static.data stay dynamic so
        # the serialized artifact serves any batch size
        exp = jax_export.export(jax.jit(fwd))(*_specs(True))
    except Exception:
        # program not shape-polymorphic (e.g. hard reshape) — pin shapes
        exp = jax_export.export(jax.jit(fwd))(*_specs(False))

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    # safe container (magic + JSON + raw StableHLO) — NOT pickle: a pickle
    # would execute arbitrary code at load and silently masquerade as the
    # reference's protobuf ProgramDesc format (framework/artifact.py).
    from ..framework.artifact import write_artifact
    write_artifact(path_prefix + ".pdmodel", {
        "format": "paddle_tpu.static_inference.v2",
        "feed_names": [getattr(v, "name", f"feed_{i}")
                       for i, v in enumerate(feed_vars)],
        "fetch_names": [getattr(v, "name", f"fetch_{i}")
                        for i, v in enumerate(fetch_vars)],
        "feed_specs": [(list(v._data.shape), str(v._data.dtype))
                       for v in feed_vars],
    }, blobs={"stablehlo": exp.serialize()})


class _LoadedProgram:
    def __init__(self, payload):
        from jax import export as jax_export
        self._exported = jax_export.deserialize(payload["stablehlo"])
        self.feed_names: List[str] = payload["feed_names"]
        self.fetch_names: List[str] = payload["fetch_names"]
        self.feed_specs = payload.get("feed_specs", [])

    def _run(self, feed: Dict[str, Any], return_numpy: bool = True):
        import jax.numpy as jnp
        args = [jnp.asarray(feed[n].numpy() if isinstance(feed[n], Tensor)
                            else feed[n]) for n in self.feed_names]
        outs = self._exported.call(*args)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def load_inference_model(path_prefix: str, executor=None):
    """Returns [program, feed_target_names, fetch_targets] (reference
    contract); ``program`` is runnable via Executor.run(program, feed=...)."""
    from ..framework.artifact import read_model_payload
    payload = read_model_payload(path_prefix + ".pdmodel")
    prog = _LoadedProgram(payload)
    return [prog, prog.feed_names, prog.fetch_names]


from . import nn  # noqa: F401,E402  (control flow: while_loop/cond/case/switch_case)


# ---------------------------------------------------------------------------
# Utility surface: gradients / guards / py_func / create_parameter / metrics
# (reference: python/paddle/static/ + python/paddle/base/backward.py)
# ---------------------------------------------------------------------------

def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Sum-of-targets gradients w.r.t. inputs (parity: paddle.static.gradients).

    The record/replay design keeps eager tensors behind the program, so this
    is the autograd engine's ``grad`` over the captured tape.
    """
    from ..core.autograd import grad as _grad

    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(target_gradients,
                                                       (list, tuple)):
        target_gradients = [target_gradients]
    hook = _tensor_mod._op_graph_hook
    _tensor_mod._op_graph_hook = None  # the grad pass is not program ops
    try:
        return list(_grad(list(targets), list(inputs),
                          grad_outputs=target_gradients, allow_unused=True))
    finally:
        _tensor_mod._op_graph_hook = hook


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Parity: records (param, grad-slot) pairs; grads materialize when the
    Executor replays the minimize step."""
    params = parameter_list
    if params is None:
        params = [t for t in _current_program().list_vars()
                  if not t.stop_gradient]
    return [(p, getattr(p, "grad", None)) for p in params]


@contextlib.contextmanager
def scope_guard(scope):
    """Variable scopes collapse onto live tensors here; the guard simply
    swaps the lookup table used by global_scope()."""
    global _scope
    old, _scope = _scope, scope
    try:
        yield
    finally:
        _scope = old


@contextlib.contextmanager
def name_scope(prefix: str = None):
    from ..utils import unique_name
    with unique_name.guard(f"{prefix}/" if prefix else None):
        yield


@contextlib.contextmanager
def device_guard(device: str = None):
    """Pin ops to 'cpu'/'gpu'(=tpu) within the block (best-effort: XLA owns
    placement inside a compiled program; eager factories honor it)."""
    from .. import device as _device_mod
    if device is None:
        yield
        return
    old = _device_mod.get_device()
    try:
        _device_mod.set_device("cpu" if device == "cpu" else "tpu"
                               if _device_mod.is_compiled_with_tpu() else "cpu")
        yield
    finally:
        _device_mod.set_device(old)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-callback op (reference: paddle.static.py_func over
    PyFuncRegistry): runs ``func`` on host numpy values. Under jit this
    lowers to ``jax.pure_callback`` (XLA host callout)."""
    import jax
    from ..core.tensor import apply as _apply

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o._data.shape), o._data.dtype)
              for o in outs]

    def kernel(*arrays):
        def host(*np_arrays):
            r = func(*np_arrays)
            r = r if isinstance(r, (list, tuple)) else [r]
            return tuple(np.asarray(v) for v in r)
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            # under jit: lower to an XLA host callout
            res = jax.pure_callback(host, tuple(shapes), *arrays)
        else:  # eager: run on host directly (axon PJRT lacks send/recv)
            import jax.numpy as jnp
            res = tuple(jnp.asarray(v) for v in host(*(np.asarray(a)
                                                       for a in arrays)))
        return tuple(res) if len(outs) > 1 else res[0]

    result = _apply("py_func", kernel, *[Executor._to_tensor(t) for t in xs],
                    differentiable=False)
    res_t = result if isinstance(result, tuple) else (result,)
    for o, r in zip(outs, res_t):
        o._rebind(r)
    return out


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.tensor import Parameter
    from ..nn import initializer as I

    init = default_initializer
    if init is None and attr is not None:
        init = getattr(attr, "initializer", None)
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierUniform()
    from ..core.dtype import convert_dtype
    p = Parameter(init(tuple(int(s) for s in shape), convert_dtype(dtype)),
                  name=name)
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                        convert_dtype(dtype)), stop_gradient=True)
    t.name = name
    t.persistable = persistable
    return t


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy as a tensor (parity: paddle.static.accuracy)."""
    import jax.numpy as jnp
    from ..core.tensor import apply as _apply

    def f(pred, lab):
        topk = jnp.argsort(pred, axis=-1)[..., ::-1][..., :k]
        lab2 = lab.reshape(lab.shape[0], -1)[:, :1]
        hit = jnp.any(topk == lab2, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return _apply("accuracy", f, Executor._to_tensor(input),
                  Executor._to_tensor(label), differentiable=False)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC via thresholded confusion counts (parity shape: returns
    (auc_out, batch_auc_out, state...) reduced to the auc tensor here)."""
    import jax.numpy as jnp
    from ..core.tensor import apply as _apply

    def f(pred, lab):
        score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 else pred.reshape(-1)
        lab2 = lab.reshape(-1).astype(jnp.float32)
        thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
        pos = (score[None, :] >= thresholds[:, None]).astype(jnp.float32)
        tp = jnp.sum(pos * lab2[None, :], axis=1)
        fp = jnp.sum(pos * (1 - lab2)[None, :], axis=1)
        tpr = tp / jnp.clip(jnp.sum(lab2), 1e-6, None)
        fpr = fp / jnp.clip(jnp.sum(1 - lab2), 1e-6, None)
        return -jnp.trapezoid(tpr, fpr)

    return _apply("auc", f, Executor._to_tensor(input),
                  Executor._to_tensor(label), differentiable=False)


__all__ += ["gradients", "append_backward", "scope_guard", "name_scope",
            "device_guard", "py_func", "create_parameter",
            "create_global_var", "accuracy", "auc"]


# ---------------------------------------------------------------------------
# Static-mode module aliases + small utilities (reference: python/paddle/
# static/__init__.py exports)
# ---------------------------------------------------------------------------

from .. import amp  # noqa: E402,F401  (static.amp == the amp package)
from ..incubate import asp as sparsity  # noqa: E402,F401


class ExponentialMovingAverage:
    """EMA of parameter values with apply/restore (reference:
    paddle.static.ExponentialMovingAverage)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        import jax.numpy as jnp
        self.decay = float(decay)
        self._ema: dict = {}
        self._backup: dict = {}
        self._jnp = jnp

    def update(self, parameters=None):
        params = parameters or [
            t for t in _default_main.list_vars() if not t.stop_gradient]
        for p in params:
            cur = self._ema.get(id(p))
            new = (p._data.astype("float32") if cur is None
                   else self.decay * cur + (1 - self.decay) *
                   p._data.astype("float32"))
            self._ema[id(p)] = new
        self._params = params

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def cm():
            for p in getattr(self, "_params", []):
                self._backup[id(p)] = p._data
                p._set_data(self._ema[id(p)].astype(p._data.dtype))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return cm()

    def restore(self, executor=None):
        for p in getattr(self, "_params", []):
            bk = self._backup.pop(id(p), None)
            if bk is not None:
                p._set_data(bk)


import contextlib as _ctx  # noqa: E402


@_ctx.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    """IPU-only sharding annotation upstream; no-op on TPU (mesh shardings
    come from pjit specs)."""
    yield


def setitem(x, index, value):
    """Functional __setitem__ (reference: paddle.static.setitem)."""
    x[index] = value
    return x


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print op (reference: paddle.static.Print). Eagerly prints and
    returns the input so program capture keeps flowing."""
    msg = f"{message or ''} {input.name if print_tensor_name else ''}".strip()
    try:
        print(f"[static.Print] {msg} shape={input.shape} "
              f"values={np.asarray(input._data).reshape(-1)[:summarize]}")
    except Exception:
        print(f"[static.Print] {msg} <unavailable while tracing>")
    return input


class WeightNormParamAttr:
    """Parity container (reference: paddle.static.WeightNormParamAttr):
    weight-norm reparameterization is applied via nn.utils.weight_norm in
    this build; the attr carries the config through."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


__all__ += ["sparsity", "ExponentialMovingAverage", "ipu_shard_guard",
            "setitem", "Print", "WeightNormParamAttr", "amp"]
