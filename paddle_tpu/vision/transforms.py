"""Vision transforms (numpy-based, host-side; parity:
python/paddle/vision/transforms/). Operate on HWC uint8/float numpy arrays
(or CHW float); composed in the DataLoader worker before device transfer.
"""

from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "ColorJitter", "Grayscale",
]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img)
        if a.dtype == np.uint8:
            a = a.astype(np.float32) / 255.0
        if a.ndim == 2:
            a = a[:, :, None]
        if self.data_format == "CHW":
            a = a.transpose(2, 0, 1)
        return np.ascontiguousarray(a, np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (a - self.mean.reshape(shape)) / self.std.reshape(shape)


_RESIZE_METHODS = {
    "nearest": "nearest",
    "bilinear": "linear",
    "linear": "linear",
    "bicubic": "cubic",
    "cubic": "cubic",
    "lanczos": "lanczos3",
}


def _resize_np(a: np.ndarray, size, interpolation="bilinear") -> np.ndarray:
    import jax
    import jax.numpy as jnp
    try:
        method = _RESIZE_METHODS[interpolation]
    except KeyError:
        raise ValueError(
            f"unsupported interpolation {interpolation!r}; "
            f"one of {sorted(_RESIZE_METHODS)}")
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
    if isinstance(size, (tuple, list)):
        h, w = size
    else:
        # int size: scale the SHORTER edge to `size`, keep aspect ratio
        # (reference semantics — torchvision/paddle.vision Resize)
        if H <= W:
            h, w = int(size), max(int(round(size * W / H)), 1)
        else:
            h, w = max(int(round(size * H / W)), 1), int(size)
    if chw:
        out_shape = (a.shape[0], h, w)
    elif a.ndim == 3:
        out_shape = (h, w, a.shape[2])
    else:
        out_shape = (h, w)
    return np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32), out_shape,
                                       method=method)).astype(a.dtype)


class Resize(BaseTransform):
    """Resize to ``size``. An int resizes the shorter edge preserving aspect
    ratio (upstream paddle.vision.transforms.Resize semantics); a (h, w)
    pair resizes to exactly that shape."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        th, tw = self.size
        i, j = max((H - th) // 2, 0), max((W - tw) // 2, 0)
        return a[:, i:i + th, j:j + tw] if chw else a[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pads = ((0, 0), (p, p), (p, p)) if chw else \
                ((p, p), (p, p)) + (((0, 0),) if a.ndim == 3 else ())
            a = np.pad(a, pads)
        H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        th, tw = self.size
        i = np.random.randint(0, H - th + 1)
        j = np.random.randint(0, W - tw + 1)
        return a[:, i:i + th, j:j + tw] if chw else a[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        a = np.asarray(img)
        if np.random.rand() < self.prob:
            return a[..., ::-1].copy() if a.ndim == 3 and a.shape[0] in (1, 3, 4) \
                else a[:, ::-1].copy()
        return a


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        a = np.asarray(img)
        if np.random.rand() < self.prob:
            chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
            return a[:, ::-1].copy() if chw else a[::-1].copy()
        return a


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                crop = a[:, i:i + h, j:j + w] if chw else a[i:i + h, j:j + w]
                return _resize_np(crop, self.size)
        return _resize_np(CenterCrop(min(H, W))(a), self.size)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        if chw:
            return np.pad(a, ((0, 0), (p[1], p[3]), (p[0], p[2])))
        pads = ((p[1], p[3]), (p[0], p[2])) + (((0, 0),) if a.ndim == 3 else ())
        return np.pad(a, pads)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue (reference semantics: one
    random factor per property, applied in order)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        if not 0 <= hue <= 0.5:
            raise ValueError("hue must be in [0, 0.5]")
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def __call__(self, img):
        # the value range is inferred ONCE: per-op re-inference would flip
        # from the 255 to the 1.0 range after a strong darkening and clip
        # the image to garbage
        a, scale = _as_float(img)
        if self.brightness:
            a = _adjust_brightness(
                a, np.random.uniform(max(0.0, 1 - self.brightness),
                                     1 + self.brightness), scale)
        if self.contrast:
            a = _adjust_contrast(
                a, np.random.uniform(max(0.0, 1 - self.contrast),
                                     1 + self.contrast), scale)
        if self.saturation and a.ndim == 3:
            a = _adjust_saturation(
                a, np.random.uniform(max(0.0, 1 - self.saturation),
                                     1 + self.saturation), scale)
        if self.hue and a.ndim == 3:
            a = adjust_hue(a, np.random.uniform(-self.hue, self.hue),
                           _scale=scale)
        return a


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        w = np.array([0.299, 0.587, 0.114], np.float32)
        g = np.tensordot(w, a, axes=([0], [0])) if chw else a @ w
        g = g[None] if chw else g[..., None]
        reps = [self.n, 1, 1] if chw else [1, 1, self.n]
        return np.tile(g, reps)


def _as_float(img):
    a = np.asarray(img, np.float32)
    scale = 255.0 if a.max() > 1.5 else 1.0
    return a, scale


def adjust_gamma(img, gamma, gain=1.0):
    """Gamma correction (reference: F.adjust_gamma)."""
    a, scale = _as_float(img)
    return np.clip(gain * scale * (a / scale) ** gamma, 0, scale)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the (i:i+h, j:j+w) region with value ``v`` (reference:
    transforms.erase). Accepts HWC or CHW numpy arrays / Tensors."""
    from ..core.tensor import Tensor

    if isinstance(img, Tensor):
        import jax.numpy as jnp
        a = np.array(img.numpy())
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        if chw:
            a[:, i:i + h, j:j + w] = v
        else:
            a[i:i + h, j:j + w] = v
        return Tensor(jnp.asarray(a))
    a = np.array(img)
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    if chw:
        a[:, i:i + h, j:j + w] = v
    else:
        a[i:i + h, j:j + w] = v
    return a


def _sample_grid(a, sx, sy, fill=0, interpolation="nearest"):
    """Gather image values at fractional source coords (sy, sx); positions
    outside the image get ``fill``."""
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    hw = a.shape[1:3] if chw else a.shape[:2]
    h, w = int(hw[0]), int(hw[1])
    valid = (sx >= 0) & (sx <= w - 1) & (sy >= 0) & (sy <= h - 1)

    def gather(syi, sxi):
        return a[:, syi, sxi] if chw else a[syi, sxi]

    if interpolation in ("bilinear", "linear"):
        x0 = np.clip(np.floor(sx).astype(np.int64), 0, w - 1)
        y0 = np.clip(np.floor(sy).astype(np.int64), 0, h - 1)
        x1, y1 = np.minimum(x0 + 1, w - 1), np.minimum(y0 + 1, h - 1)
        fx = (np.clip(sx, 0, w - 1) - x0).astype(np.float32)
        fy = (np.clip(sy, 0, h - 1) - y0).astype(np.float32)
        if chw:
            fx, fy = fx[None], fy[None]
        elif a.ndim == 3:
            fx, fy = fx[..., None], fy[..., None]
        out = ((1 - fy) * ((1 - fx) * gather(y0, x0) + fx * gather(y0, x1))
               + fy * ((1 - fx) * gather(y1, x0) + fx * gather(y1, x1)))
        if np.issubdtype(a.dtype, np.integer):
            out = np.round(out)  # truncation would bias every sample low
    else:
        sxi = np.clip(np.round(sx).astype(np.int64), 0, w - 1)
        syi = np.clip(np.round(sy).astype(np.int64), 0, h - 1)
        out = gather(syi, sxi)
    if chw:
        mask = valid[None]
    else:
        mask = valid[..., None] if a.ndim == 3 else valid
    return np.where(mask, out, fill).astype(a.dtype)


def _affine_sample(a, matrix, fill=0, interpolation="nearest"):
    """Inverse-warp HWC/CHW array with a 2x3 affine matrix."""
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    hw = a.shape[1:3] if chw else a.shape[:2]
    h, w = int(hw[0]), int(hw[1])
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    # center-origin coordinates
    xc, yc = xs - (w - 1) / 2.0, ys - (h - 1) / 2.0
    m = np.asarray(matrix, np.float32).reshape(2, 3)
    sx = m[0, 0] * xc + m[0, 1] * yc + m[0, 2] + (w - 1) / 2.0
    sy = m[1, 0] * xc + m[1, 1] * yc + m[1, 2] + (h - 1) / 2.0
    return _sample_grid(a, sx, sy, fill=fill, interpolation=interpolation)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping ``startpoints`` -> ``endpoints``."""
    a = np.asarray(img)
    sp = np.asarray(startpoints, np.float32)
    ep = np.asarray(endpoints, np.float32)
    # solve the 8-dof homography sending endpoints back to startpoints
    A, b = [], []
    for (x, y), (u, v) in zip(ep, sp):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        b.append(u)
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        b.append(v)
    coef = np.linalg.solve(np.asarray(A, np.float32),
                           np.asarray(b, np.float32))
    hmat = np.append(coef, 1.0).reshape(3, 3)
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    hw = a.shape[1:3] if chw else a.shape[:2]
    h, w = int(hw[0]), int(hw[1])
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xs)
    pts = np.stack([xs, ys, ones], axis=-1).reshape(-1, 3).T
    src = hmat @ pts
    sx = (src[0] / src[2]).reshape(h, w)
    sy = (src[1] / src[2]).reshape(h, w)
    return _sample_grid(a, sx, sy, fill=fill, interpolation=interpolation)


class RandomErasing(BaseTransform):
    """Erase a random rectangle (reference: transforms.RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value = value

    def __call__(self, img):
        a = np.asarray(img)
        if np.random.rand() > self.prob:
            return a
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        h, w = (a.shape[1:3] if chw else a.shape[:2])
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                return erase(a, i, j, eh, ew, self.value)
        return a


class RandomAffine(BaseTransform):
    """Random rotation/translate/scale/shear (reference:
    transforms.RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        self.degrees = (degrees if isinstance(degrees, (list, tuple))
                        else (-degrees, degrees))
        self.translate, self.scale_rng = translate, scale
        if shear is None:
            self.shear = None
        elif isinstance(shear, (int, float)):
            self.shear = (-float(shear), float(shear))
        else:
            self.shear = tuple(shear)
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def __call__(self, img):
        a = np.asarray(img)
        ang = np.random.uniform(*self.degrees)
        sc = (np.random.uniform(*self.scale_rng)
              if self.scale_rng else 1.0)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        h, w = (a.shape[1:3] if chw else a.shape[:2])
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        if self.shear is not None:
            shx = np.random.uniform(*self.shear[:2])
            shy = (np.random.uniform(*self.shear[2:4])
                   if len(self.shear) == 4 else 0.0)
        else:
            shx = shy = 0.0
        return _affine_from_params(a, ang, (tx, ty), sc, (shx, shy),
                                   interpolation=self.interpolation,
                                   fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob, self.d = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def __call__(self, img):
        a = np.asarray(img)
        if np.random.rand() > self.prob:
            return a
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        h, w = (a.shape[1:3] if chw else a.shape[:2])
        dx, dy = self.d * w / 2, self.d * h / 2
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        jitter = lambda: (np.random.uniform(0, dx), np.random.uniform(0, dy))
        end = [(0 + jitter()[0], 0 + jitter()[1]),
               (w - 1 - jitter()[0], 0 + jitter()[1]),
               (w - 1 - jitter()[0], h - 1 - jitter()[1]),
               (0 + jitter()[0], h - 1 - jitter()[1])]
        return perspective(a, start, end, interpolation=self.interpolation,
                           fill=self.fill)


class RandAugment(BaseTransform):
    """RandAugment (reference: transforms.RandAugment): N random ops at
    magnitude M from the standard pool (geometric + photometric subset that
    is meaningful on raw arrays)."""

    def __init__(self, num_ops=2, magnitude=9, num_magnitude_bins=31,
                 interpolation="nearest", fill=0):
        self.num_ops, self.m = num_ops, magnitude / max(num_magnitude_bins - 1, 1)

    def _ops(self):
        m = self.m
        return [
            lambda a: adjust_gamma(a, 1.0 + (np.random.rand() - 0.5) * m),
            lambda a: np.clip(np.asarray(a, np.float32) *
                              (1 + (np.random.rand() - 0.5) * m), 0,
                              255 if np.asarray(a).max() > 1.5 else 1.0),
            lambda a: _affine_sample(np.asarray(a),
                                     [1, m * (np.random.rand() - 0.5), 0,
                                      0, 1, 0]),  # shear-x
            lambda a: _affine_sample(np.asarray(a),
                                     [1, 0, 0,
                                      m * (np.random.rand() - 0.5), 1, 0]),
            lambda a: _affine_sample(
                np.asarray(a),
                [np.cos(0.5 * m), -np.sin(0.5 * m), 0,
                 np.sin(0.5 * m), np.cos(0.5 * m), 0]),  # rotate
        ]

    def __call__(self, img):
        a = np.asarray(img)
        ops = self._ops()
        for _ in range(self.num_ops):
            a = ops[np.random.randint(len(ops))](a)
        return a


class AutoAugment(RandAugment):
    """AutoAugment policy surface (reference: transforms.AutoAugment); the
    learned ImageNet policy collapses onto the same op pool here."""

    def __init__(self, policy="imagenet", interpolation="nearest", fill=0):
        super().__init__(num_ops=2, magnitude=9)


# ---------------------------------------------------------------------------
# functional surface (reference: paddle.vision.transforms.functional / the
# F.* names re-exported at transforms level) + the photometric transform
# classes built on it. All numpy/HWC-or-CHW, matching this module's model.
# ---------------------------------------------------------------------------

def hflip(img):
    a = np.asarray(img)
    if a.ndim == 2:
        return a[:, ::-1]
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    return a[:, :, ::-1] if chw else a[:, ::-1]


def vflip(img):
    a = np.asarray(img)
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    return a[:, ::-1] if chw else a[::-1]


def crop(img, top, left, height, width):
    a = np.asarray(img)
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    if chw:
        return a[:, top:top + height, left:left + width]
    return a[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = np.asarray(img)
    oh, ow = ((output_size, output_size)
              if isinstance(output_size, int) else output_size)
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    h, w = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
    top, left = max((h - oh) // 2, 0), max((w - ow) // 2, 0)
    return crop(a, top, left, oh, ow)


def resize(img, size, interpolation="bilinear"):
    return _resize_np(np.asarray(img), size, interpolation)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if to_rgb:  # reference: flip BGR -> RGB before normalizing
        a = a[::-1] if data_format == "CHW" else a[..., ::-1]
    if data_format == "CHW":
        return (a - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    return (a - mean) / std


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def _adjust_brightness(a, factor, scale):
    return np.clip(a * float(factor), 0, scale)


def _adjust_contrast(a, factor, scale):
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    w = np.array([0.299, 0.587, 0.114], np.float32)
    if a.ndim == 2:
        mean = a.mean()
    else:
        gray = (np.tensordot(w, a, axes=([0], [0])) if chw else a @ w)
        mean = gray.mean()
    return np.clip((a - mean) * float(factor) + mean, 0, scale)


def _adjust_saturation(a, factor, scale):
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    w = np.array([0.299, 0.587, 0.114], np.float32)
    gray = (np.tensordot(w, a, axes=([0], [0]))[None] if chw
            else (a @ w)[..., None])
    return np.clip(gray + float(factor) * (a - gray), 0, scale)


def adjust_brightness(img, brightness_factor):
    a, scale = _as_float(img)
    return _adjust_brightness(a, brightness_factor, scale)


def adjust_contrast(img, contrast_factor):
    a, scale = _as_float(img)
    return _adjust_contrast(a, contrast_factor, scale)


def adjust_saturation(img, saturation_factor):
    a, scale = _as_float(img)
    return _adjust_saturation(a, saturation_factor, scale)


def adjust_hue(img, hue_factor, _scale=None):
    """Shift hue by ``hue_factor`` (in [-0.5, 0.5] turns) via RGB->HSV."""
    if not -0.5 <= float(hue_factor) <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    if _scale is None:
        a, scale = _as_float(img)
    else:
        a, scale = np.asarray(img, np.float32), _scale
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    rgb = (np.moveaxis(a, 0, -1) if chw else a) / scale
    mx, mn = rgb.max(-1), rgb.min(-1)
    diff = mx - mn
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    safe = np.where(diff == 0, 1.0, diff)
    h = np.where(mx == r, ((g - b) / safe) % 6,
                 np.where(mx == g, (b - r) / safe + 2, (r - g) / safe + 4))
    h = np.where(diff == 0, 0.0, h) / 6.0
    h = (h + float(hue_factor)) % 1.0
    s = np.where(mx == 0, 0.0, diff / np.where(mx == 0, 1.0, mx))
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i.astype(np.int64) % 6
    out = np.select(
        [(i == k)[..., None] for k in range(6)],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = np.clip(out * scale, 0, scale).astype(np.float32)
    return np.moveaxis(out, -1, 0) if chw else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by ``angle`` degrees counter-clockwise (reference
    convention — verified against np.rot90 for the 90-degree case) about
    ``center`` (image center by default). expand=True output-resizing is
    not implemented — pre-pad instead."""
    if expand:
        raise NotImplementedError(
            "rotate(expand=True) is not implemented; pad the image to the "
            "rotated bounding box first")
    a = np.asarray(img)
    rad = np.deg2rad(angle)  # backward warp: sample with the inverse (CW)
    m = [np.cos(rad), -np.sin(rad), 0.0, np.sin(rad), np.cos(rad), 0.0]
    if center is not None:
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        h, w = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        cx, cy = center[0] - (w - 1) / 2.0, center[1] - (h - 1) / 2.0
        # shift so rotation pivots on `center` instead of the image center
        m[2] = cx - (m[0] * cx + m[1] * cy)
        m[5] = cy - (m[3] * cx + m[4] * cy)
    return _affine_sample(a, m, fill=fill, interpolation=interpolation)


def affine(img, angle=0, translate=(0, 0), scale=1.0, shear=(0, 0),
           interpolation="nearest", fill=0, center=None):
    return _affine_from_params(np.asarray(img), angle, translate, scale,
                               shear, interpolation=interpolation, fill=fill,
                               center=center)


def _affine_from_params(a, angle, translate, scale, shear,
                        interpolation="nearest", fill=0, center=None):
    """Backward-warp matrix for the forward transform
    ``y = s * R(angle) @ Sh(shear) @ (x - c) + c + translate`` (rotation
    convention matching :func:`rotate`, CCW-positive): the sampling matrix
    is the exact inverse ``x = (1/s) Sh^-1 R^-1 (y - c - t) + c``."""
    rad = np.deg2rad(angle)
    shx, shy = (np.deg2rad(shear[0]), np.deg2rad(shear[1])) \
        if isinstance(shear, (tuple, list)) else (np.deg2rad(shear), 0.0)
    # inverse rotation: rotate() verified backward R(+rad) == forward CCW
    rot_inv = np.array([[np.cos(rad), -np.sin(rad)],
                        [np.sin(rad), np.cos(rad)]], np.float32)
    tx_, ty_ = np.tan(shx), np.tan(shy)
    sh_inv = np.array([[1.0, -tx_], [-ty_, 1.0]], np.float32) \
        / (1.0 - tx_ * ty_)
    lin = (sh_inv @ rot_inv) / float(scale)
    if center is not None:
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        h, w = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        cx = float(center[0]) - (w - 1) / 2.0
        cy = float(center[1]) - (h - 1) / 2.0
    else:
        cx = cy = 0.0
    tcx, tcy = float(translate[0]) + cx, float(translate[1]) + cy
    m = [lin[0, 0], lin[0, 1], cx - (lin[0, 0] * tcx + lin[0, 1] * tcy),
         lin[1, 0], lin[1, 1], cy - (lin[1, 0] * tcx + lin[1, 1] * tcy)]
    return _affine_sample(a, m, fill=fill, interpolation=interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, expand=self.expand,
                      center=self.center, fill=self.fill)


__all__ += ["hflip", "vflip", "crop", "center_crop", "resize", "pad",
            "normalize", "to_tensor", "to_grayscale", "adjust_brightness",
            "adjust_contrast", "adjust_saturation", "adjust_hue", "rotate",
            "affine", "BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "RandomRotation"]
