"""Vision transforms (numpy-based, host-side; parity:
python/paddle/vision/transforms/). Operate on HWC uint8/float numpy arrays
(or CHW float); composed in the DataLoader worker before device transfer.
"""

from __future__ import annotations

import numbers
from typing import List, Sequence

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "ColorJitter", "Grayscale",
]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img)
        if a.dtype == np.uint8:
            a = a.astype(np.float32) / 255.0
        if a.ndim == 2:
            a = a[:, :, None]
        if self.data_format == "CHW":
            a = a.transpose(2, 0, 1)
        return np.ascontiguousarray(a, np.float32)


class Normalize(BaseTransform):
    def __init__(self, mean, std, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (a - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(a: np.ndarray, size) -> np.ndarray:
    import jax
    import jax.numpy as jnp
    h, w = size if isinstance(size, (tuple, list)) else (size, size)
    chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
    if chw:
        out_shape = (a.shape[0], h, w)
    elif a.ndim == 3:
        out_shape = (h, w, a.shape[2])
    else:
        out_shape = (h, w)
    return np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32), out_shape,
                                       method="linear")).astype(a.dtype)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        th, tw = self.size
        i, j = max((H - th) // 2, 0), max((W - tw) // 2, 0)
        return a[:, i:i + th, j:j + tw] if chw else a[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        if self.padding:
            p = self.padding
            pads = ((0, 0), (p, p), (p, p)) if chw else \
                ((p, p), (p, p)) + (((0, 0),) if a.ndim == 3 else ())
            a = np.pad(a, pads)
        H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        th, tw = self.size
        i = np.random.randint(0, H - th + 1)
        j = np.random.randint(0, W - tw + 1)
        return a[:, i:i + th, j:j + tw] if chw else a[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        a = np.asarray(img)
        if np.random.rand() < self.prob:
            return a[..., ::-1].copy() if a.ndim == 3 and a.shape[0] in (1, 3, 4) \
                else a[:, ::-1].copy()
        return a


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        a = np.asarray(img)
        if np.random.rand() < self.prob:
            chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
            return a[:, ::-1].copy() if chw else a[::-1].copy()
        return a


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = size if isinstance(size, (tuple, list)) else (size, size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        a = np.asarray(img)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        H, W = (a.shape[1], a.shape[2]) if chw else (a.shape[0], a.shape[1])
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                crop = a[:, i:i + h, j:j + w] if chw else a[i:i + h, j:j + w]
                return _resize_np(crop, self.size)
        return _resize_np(CenterCrop(min(H, W))(a), self.size)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        if chw:
            return np.pad(a, ((0, 0), (p[1], p[3]), (p[0], p[2])))
        pads = ((p[1], p[3]), (p[0], p[2])) + (((0, 0),) if a.ndim == 3 else ())
        return np.pad(a, pads)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        if self.brightness:
            a = a * np.random.uniform(1 - self.brightness, 1 + self.brightness)
        if self.contrast:
            m = a.mean()
            a = (a - m) * np.random.uniform(1 - self.contrast, 1 + self.contrast) + m
        return np.clip(a, 0, 255 if a.max() > 1.5 else 1.0)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        a = np.asarray(img, np.float32)
        chw = a.ndim == 3 and a.shape[0] in (1, 3, 4)
        w = np.array([0.299, 0.587, 0.114], np.float32)
        g = np.tensordot(w, a, axes=([0], [0])) if chw else a @ w
        g = g[None] if chw else g[..., None]
        reps = [self.n, 1, 1] if chw else [1, 1, self.n]
        return np.tile(g, reps)
