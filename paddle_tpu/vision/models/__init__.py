from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152, wide_resnet50_2)
from .lenet import LeNet  # noqa: F401
from .mobilenet import MobileNetV3Small  # noqa: F401
