"""ShuffleNetV2 (parity: python/paddle/vision/models/shufflenetv2.py)."""

from __future__ import annotations

from ... import nn
from ...ops.conv_pool import channel_shuffle as _channel_shuffle
from ...ops.manipulation import concat

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0"]


def _conv_bn_act(in_c, out_c, k, stride=1, padding=0, groups=1, act=True):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act:
        layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn_act(branch_c, branch_c, 1),
                _conv_bn_act(branch_c, branch_c, 3, stride=1, padding=1,
                             groups=branch_c, act=False),
                _conv_bn_act(branch_c, branch_c, 1))
        else:
            self.branch1 = nn.Sequential(
                _conv_bn_act(in_c, in_c, 3, stride=stride, padding=1,
                             groups=in_c, act=False),
                _conv_bn_act(in_c, branch_c, 1))
            self.branch2 = nn.Sequential(
                _conv_bn_act(in_c, branch_c, 1),
                _conv_bn_act(branch_c, branch_c, 3, stride=stride, padding=1,
                             groups=branch_c, act=False),
                _conv_bn_act(branch_c, branch_c, 1))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    _STAGE_OUT = {
        0.25: (24, 24, 48, 96, 512), 0.5: (24, 48, 96, 192, 1024),
        1.0: (24, 116, 232, 464, 1024), 1.5: (24, 176, 352, 704, 1024),
        2.0: (24, 244, 488, 976, 2048)}

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True, act=None):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stem_c, c2, c3, c4, last_c = self._STAGE_OUT[scale]
        self.conv1 = _conv_bn_act(3, stem_c, 3, stride=2, padding=1)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = stem_c
        for out_c, repeats in ((c2, 4), (c3, 8), (c4, 4)):
            units = [_ShuffleUnit(in_c, out_c, 2)]
            units += [_ShuffleUnit(out_c, out_c, 1) for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn_act(in_c, last_c, 1)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(last_c, num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)
