"""MobileNetV3-Small (parity: python/paddle/vision/models/mobilenetv3.py,
trimmed config)."""

from ... import nn


class _SEModule(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(channels, channels // reduction, 1)
        self.fc2 = nn.Conv2D(channels // reduction, channels, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers += [nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_c), act()]
        layers += [nn.Conv2D(exp_c, exp_c, k, stride=stride, padding=k // 2,
                             groups=exp_c, bias_attr=False),
                   nn.BatchNorm2D(exp_c), act()]
        if use_se:
            layers.append(_SEModule(exp_c))
        layers += [nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out




def _scale_c(c, scale):
    """Width-multiplier channel rounding (shared _make_divisible rule)."""
    from .mobilenetv2 import _make_divisible
    return _make_divisible(c * scale)

class MobileNetV3Small(nn.Layer):
    CFG = [
        # k, exp, out, se, act, stride
        (3, 16, 16, True, nn.ReLU, 2),
        (3, 72, 24, False, nn.ReLU, 2),
        (3, 88, 24, False, nn.ReLU, 1),
        (5, 96, 40, True, nn.Hardswish, 2),
        (5, 240, 40, True, nn.Hardswish, 1),
        (5, 240, 40, True, nn.Hardswish, 1),
        (5, 120, 48, True, nn.Hardswish, 1),
        (5, 144, 48, True, nn.Hardswish, 1),
        (5, 288, 96, True, nn.Hardswish, 2),
        (5, 576, 96, True, nn.Hardswish, 1),
        (5, 576, 96, True, nn.Hardswish, 1),
    ]

    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        sc = lambda c: _scale_c(c, scale)
        stem_c = sc(16)
        self.stem = nn.Sequential(
            nn.Conv2D(3, stem_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(stem_c), nn.Hardswish())
        blocks = []
        in_c = stem_c
        for k, exp, out, se, act, s in self.CFG:
            blocks.append(_InvertedResidual(in_c, sc(exp), sc(out), k, s, se,
                                            act))
            in_c = sc(out)
        self.blocks = nn.Sequential(*blocks)
        head_c = sc(576)
        self.head_conv = nn.Sequential(
            nn.Conv2D(in_c, head_c, 1, bias_attr=False),
            nn.BatchNorm2D(head_c), nn.Hardswish())
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(
            nn.Linear(head_c, 1024), nn.Hardswish(), nn.Dropout(0.2),
            nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.head_conv(self.blocks(self.stem(x)))
        x = self.pool(x)
        from ...ops.manipulation import flatten
        return self.classifier(flatten(x, 1))


class MobileNetV3Large(nn.Layer):
    """Parity: python/paddle/vision/models/mobilenetv3.py (large config)."""

    CFG = [
        # k, exp, out, se, act, stride
        (3, 16, 16, False, nn.ReLU, 1),
        (3, 64, 24, False, nn.ReLU, 2),
        (3, 72, 24, False, nn.ReLU, 1),
        (5, 72, 40, True, nn.ReLU, 2),
        (5, 120, 40, True, nn.ReLU, 1),
        (5, 120, 40, True, nn.ReLU, 1),
        (3, 240, 80, False, nn.Hardswish, 2),
        (3, 200, 80, False, nn.Hardswish, 1),
        (3, 184, 80, False, nn.Hardswish, 1),
        (3, 184, 80, False, nn.Hardswish, 1),
        (3, 480, 112, True, nn.Hardswish, 1),
        (3, 672, 112, True, nn.Hardswish, 1),
        (5, 672, 160, True, nn.Hardswish, 2),
        (5, 960, 160, True, nn.Hardswish, 1),
        (5, 960, 160, True, nn.Hardswish, 1),
    ]

    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()
        sc = lambda c: _scale_c(c, scale)
        stem_c = sc(16)
        self.stem = nn.Sequential(
            nn.Conv2D(3, stem_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(stem_c), nn.Hardswish())
        blocks = []
        in_c = stem_c
        for k, exp, out, se, act, s in self.CFG:
            blocks.append(_InvertedResidual(in_c, sc(exp), sc(out), k, s, se,
                                            act))
            in_c = sc(out)
        self.blocks = nn.Sequential(*blocks)
        head_c = sc(960)
        self.head_conv = nn.Sequential(
            nn.Conv2D(in_c, head_c, 1, bias_attr=False),
            nn.BatchNorm2D(head_c), nn.Hardswish())
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(
            nn.Linear(head_c, 1280), nn.Hardswish(), nn.Dropout(0.2),
            nn.Linear(1280, num_classes))

    def forward(self, x):
        x = self.head_conv(self.blocks(self.stem(x)))
        x = self.pool(x)
        from ...ops.manipulation import flatten
        return self.classifier(flatten(x, 1))
