"""MobileNetV2 (parity: python/paddle/vision/models/mobilenetv2.py)."""

from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _InvertedResidualV2(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(in_c, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [
            nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                      groups=hidden, bias_attr=False),
            nn.BatchNorm2D(hidden), nn.ReLU6(),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    CFG = [  # t (expand), c, n (repeats), s (stride)
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
                  nn.BatchNorm2D(in_c), nn.ReLU6()]
        for t, c, n, s in self.CFG:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidualV2(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers += [nn.Conv2D(in_c, last_c, 1, bias_attr=False),
                   nn.BatchNorm2D(last_c), nn.ReLU6()]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
