"""``paddle.vision`` capability surface (PaddleClas-adjacent).

Parity: python/paddle/vision/ (models, transforms, datasets).
"""

from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
