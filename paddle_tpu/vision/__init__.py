"""``paddle.vision`` capability surface (PaddleClas-adjacent).

Parity: python/paddle/vision/ (models, transforms, datasets).
"""

from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401


_image_backend = "numpy"


def set_image_backend(backend: str) -> None:
    """Parity: paddle.vision.set_image_backend ('pil'/'cv2' upstream). This
    build's transforms operate on numpy arrays; the setting is recorded and
    'numpy' is always accepted."""
    global _image_backend
    if backend not in ("numpy", "pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    """Load an image file to an array (PIL when available, else raw numpy
    formats)."""
    import numpy as np
    import os

    ext = os.path.splitext(str(path))[1].lower()
    if ext in (".npy",):
        return np.load(path)
    try:
        from PIL import Image  # pillow ships with matplotlib stacks

        return Image.open(path)
    except ImportError as exc:
        raise RuntimeError(
            f"image_load({path!r}): no PIL in this build; supply .npy arrays "
            "or decode upstream") from exc
