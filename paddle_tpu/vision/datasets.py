"""Datasets (parity: python/paddle/vision/datasets/).

No network egress in this environment, so the standard names (MNIST, Cifar10,
ImageNet-folder) are backed by deterministic synthetic generators with the
right shapes/classes when the real files are absent; when a local copy exists
(``data_file``/``root`` argument) the genuine files are read.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageNet",
           "DatasetFolder"]


class _SyntheticImages(Dataset):
    """Deterministic class-conditional gaussian images — loss actually
    decreases when training, which makes it a usable CI stand-in."""

    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        self.n = n
        self.shape = shape
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.default_rng(seed)
        self.class_means = rng.normal(0, 1, (num_classes,) + shape).astype(np.float32)
        self._seed = seed

    def __getitem__(self, idx):
        label = idx % self.num_classes
        rng = np.random.default_rng(self._seed + idx)
        img = self.class_means[label] + rng.normal(0, 0.5, self.shape).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.int64(label)

    def __len__(self):
        return self.n


class MNIST(_SyntheticImages):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(num, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8)
            self.transform = transform
            self.real = True
            return
        self.real = False
        n = 60000 if mode == "train" else 10000
        super().__init__(min(n, 2048), (1, 28, 28), 10, transform)

    def __getitem__(self, idx):
        if getattr(self, "real", False):
            img = self.images[idx][None].astype(np.float32) / 255.0
            if self.transform:
                img = self.transform(img)
            return img, np.int64(self.labels[idx])
        return super().__getitem__(idx)

    def __len__(self):
        if getattr(self, "real", False):
            return len(self.images)
        return super().__len__()


class FashionMNIST(MNIST):
    pass


class Cifar10(_SyntheticImages):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file and os.path.exists(data_file):
            raise NotImplementedError("real cifar archive loading: use DatasetFolder")
        n = 50000 if mode == "train" else 10000
        super().__init__(min(n, 2048), (3, 32, 32), 10, transform)


class Cifar100(_SyntheticImages):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        n = 50000 if mode == "train" else 10000
        super().__init__(min(n, 2048), (3, 32, 32), 100, transform)


class FakeImageNet(_SyntheticImages):
    """ImageNet-shaped synthetic stream for ResNet-50 benchmarking."""

    def __init__(self, n=1024, image_size=224, num_classes=1000, transform=None):
        super().__init__(n, (3, image_size, image_size), num_classes, transform)


class DatasetFolder(Dataset):
    """ImageFolder layout: root/class_x/img.npy (npy/npz images)."""

    def __init__(self, root, transform: Optional[Callable] = None,
                 extensions=(".npy",)):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.endswith(tuple(extensions)):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = np.load(path)
        if self.transform:
            img = self.transform(img)
        return img.astype(np.float32), np.int64(label)

    def __len__(self):
        return len(self.samples)


class Flowers(_SyntheticImages):
    """Flowers102 surface (synthetic-local: zero-egress build)."""

    def __init__(self, mode="train", transform=None, download=False,
                 backend=None):
        n = 1020 if mode == "train" else 1020 if mode == "valid" else 6149
        super().__init__(n, (3, 224, 224), 102, transform, seed=7)
        self.mode = mode


class VOC2012(Dataset):
    """VOC2012 segmentation surface: (image, label-mask) pairs
    (synthetic-local: class-conditional blobs with a consistent mask)."""

    def __init__(self, mode="train", transform=None, download=False,
                 backend=None):
        self.n = 1464 if mode == "train" else 1449
        self.transform = transform
        self._seed = 21

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed + idx)
        img = rng.normal(0, 1, (3, 224, 224)).astype(np.float32)
        mask = np.zeros((224, 224), np.int64)
        cls = idx % 20 + 1
        cx, cy = rng.integers(64, 160, 2)
        mask[cy - 40:cy + 40, cx - 40:cx + 40] = cls
        img[:, mask > 0] += 1.5  # the object region is visibly brighter
        if self.transform:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return self.n


class ImageFolder(Dataset):
    """Unlabeled folder of images (reference: vision.datasets.ImageFolder —
    flat list, returns [img] per sample). Reads .npy arrays; .png/.jpg when
    PIL is importable."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader
        exts = tuple(extensions or (".npy", ".png", ".jpg", ".jpeg"))
        self.samples = []
        for base, _dirs, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(base, fname)
                if is_valid_file is not None:
                    # reference passes the FULL path to the predicate
                    if is_valid_file(path):
                        self.samples.append(path)
                elif fname.lower().endswith(exts):
                    self.samples.append(path)

    def _load(self, path):
        if self.loader is not None:
            return self.loader(path)
        if path.endswith(".npy"):
            return np.load(path)
        from . import image_load
        return np.asarray(image_load(path), np.float32)

    def __getitem__(self, idx):
        img = self._load(self.samples[idx])
        if self.transform:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
