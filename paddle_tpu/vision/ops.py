"""``paddle.vision.ops`` (reference: python/paddle/vision/ops.py — roi_align,
roi_pool, nms, box ops, DeformConv2D, PSRoIPool).

TPU-native notes: ROI ops are static-shape gathers (bilinear sample grids
computed per-box with fixed output resolution — XLA-friendly, no dynamic
shapes); deformable conv samples the input at learned offsets via the same
bilinear gather; NMS reuses the padded fixed-iteration kernel from
ops/vision.py (the detection-op layer built for PP-YOLOE).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import apply
from ..nn.layer import Layer
from ..ops._helpers import ensure_tensor
from ..ops.vision import bbox_iou, box_area, multiclass_nms, nms  # noqa: F401

__all__ = ["roi_align", "roi_pool", "nms", "box_area", "bbox_iou",
           "box_coder", "DeformConv2D", "deform_conv2d", "RoIAlign",
           "RoIPool", "PSRoIPool", "psroi_pool"]


def _bilinear_sample(feat, ys, xs):
    """feat (C, H, W); ys/xs arbitrary same-shaped grids → (C, *grid)."""
    h, w = feat.shape[-2:]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = jnp.clip(y0 + dy, 0, h - 1).astype(jnp.int32)
            xx = jnp.clip(x0 + dx, 0, w - 1).astype(jnp.int32)
            # out-of-range taps contribute zero (exact torchvision/paddle
            # boundary semantics)
            valid = ((y0 + dy >= 0) & (y0 + dy <= h - 1) &
                     (x0 + dx >= 0) & (x0 + dx <= w - 1))
            tap = feat[:, yy, xx]
            out = out + tap * (wy * wx * valid)[None]
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """ROI Align (reference: phi::RoiAlignKernel). ``x`` (N,C,H,W); ``boxes``
    (R,4) x1y1x2y2 in input coords; ``boxes_num`` (N,) rois per image."""
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n = feat.shape[0]
        r = rois.shape[0]
        # map each roi to its batch image: repeat image ids by rois_num
        ends = jnp.cumsum(rois_num)
        img_id = jnp.sum(jnp.arange(r)[:, None] >= ends[None, :], axis=1)
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        s = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid per roi: (ph*s, pw*s) points, averaged per bin
        gy = (jnp.arange(ph * s) + 0.5) / s  # in bin units
        gx = (jnp.arange(pw * s) + 0.5) / s

        def one(roi_idx):
            fy = y1[roi_idx] + gy * bin_h[roi_idx]      # (ph*s,)
            fx = x1[roi_idx] + gx * bin_w[roi_idx]      # (pw*s,)
            ys = jnp.broadcast_to(fy[:, None], (ph * s, pw * s))
            xs = jnp.broadcast_to(fx[None, :], (ph * s, pw * s))
            sampled = _bilinear_sample(feat[img_id[roi_idx]], ys, xs)
            c = sampled.shape[0]
            sampled = sampled.reshape(c, ph, s, pw, s)
            return sampled.mean(axis=(2, 4))  # (C, ph, pw)

        return jax.vmap(one)(jnp.arange(r))

    return apply("roi_align", f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None):
    """ROI max-pool (reference: phi::RoiPoolKernel)."""
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        ends = jnp.cumsum(rois_num)
        img_id = jnp.sum(jnp.arange(r)[:, None] >= ends[None, :], axis=1)
        x1 = jnp.round(rois[:, 0] * spatial_scale)
        y1 = jnp.round(rois[:, 1] * spatial_scale)
        x2 = jnp.round(rois[:, 2] * spatial_scale)
        y2 = jnp.round(rois[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)

        ys_all = jnp.arange(h, dtype=jnp.float32)
        xs_all = jnp.arange(w, dtype=jnp.float32)

        def one(roi_idx):
            bin_h = rh[roi_idx] / ph
            bin_w = rw[roi_idx] / pw
            ys0 = y1[roi_idx] + jnp.arange(ph) * bin_h
            xs0 = x1[roi_idx] + jnp.arange(pw) * bin_w
            # membership mask per bin over the full H/W (static shapes)
            ymask = ((ys_all[None, :] >= jnp.floor(ys0)[:, None]) &
                     (ys_all[None, :] < jnp.ceil(ys0 + bin_h)[:, None]))
            xmask = ((xs_all[None, :] >= jnp.floor(xs0)[:, None]) &
                     (xs_all[None, :] < jnp.ceil(xs0 + bin_w)[:, None]))
            m = (ymask[:, None, :, None] & xmask[None, :, None, :])
            fimg = feat[img_id[roi_idx]]  # (C,H,W)
            big = jnp.where(m[None], fimg[:, None, None, :, :], -jnp.inf)
            return big.max(axis=(-1, -2))  # (C, ph, pw)

        return jax.vmap(one)(jnp.arange(r))

    return apply("roi_pool", f, x, boxes, boxes_num)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
               name=None):
    """Position-sensitive ROI pooling (reference: phi::PsroiPoolKernel):
    channel group (i,j) feeds output bin (i,j), average-pooled."""
    x = ensure_tensor(x)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    c = int(x.shape[1])
    if c % (ph * pw) != 0:
        raise ValueError(f"channels {c} must be divisible by "
                         f"output_size^2 {ph * pw}")
    out_c = c // (ph * pw)
    aligned = roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                        sampling_ratio=2, aligned=False)

    def f(a):
        r = a.shape[0]
        # paddle channel layout: input channel (c*ph + i)*pw + j feeds output
        # channel c at bin (i, j)
        blocks = a.reshape(r, out_c, ph, pw, ph, pw)
        ii = jnp.arange(ph)[:, None]
        jj = jnp.arange(pw)[None, :]
        return blocks[:, :, ii, jj, ii, jj]

    return apply("psroi_pool", f, aligned)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None):
    """Encode/decode boxes against priors (reference: phi::BoxCoderKernel)."""
    prior = ensure_tensor(prior_box)
    tb = ensure_tensor(target_box)
    pbv = None if prior_box_var is None else ensure_tensor(prior_box_var)
    norm = 0.0 if box_normalized else 1.0

    def f(p, t, *maybe_var):
        var = maybe_var[0] if maybe_var else jnp.ones_like(p)
        pw = p[..., 2] - p[..., 0] + norm
        ph_ = p[..., 3] - p[..., 1] + norm
        pcx = p[..., 0] + pw * 0.5
        pcy = p[..., 1] + ph_ * 0.5
        if code_type == "encode_center_size":
            tw = t[..., 2] - t[..., 0] + norm
            th = t[..., 3] - t[..., 1] + norm
            tcx = t[..., 0] + tw * 0.5
            tcy = t[..., 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph_,
                             jnp.log(tw / pw), jnp.log(th / ph_)], axis=-1)
            return out / var
        # decode
        d = t * var
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph_ + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)

    if pbv is not None:
        return apply("box_coder", f, prior, tb, pbv)
    return apply("box_coder", f, prior, tb)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: phi::DeformableConvKernel): bilinear
    sampling at offset-shifted taps, then a dense matmul per output pixel."""
    x = ensure_tensor(x)
    offset = ensure_tensor(offset)
    weight = ensure_tensor(weight)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    # InferMeta-style validation (reference: DeformableConvInferMeta in
    # paddle/phi/infermeta/multiary.cc): name the op, the offending
    # argument, and got-vs-expected shapes instead of letting a raw jax
    # reshape/broadcast error escape from deep inside the kernel body.
    def _bad(arg, expected, got):
        raise ValueError(
            f"deform_conv2d: {arg} expected {expected}, got {got}")

    if x.ndim != 4:
        _bad("x", "a 4-D NCHW tensor", f"rank {x.ndim} with shape {x.shape}")
    if weight.ndim != 4:
        _bad("weight", "a 4-D (C_out, C_in/groups, kh, kw) tensor",
             f"rank {weight.ndim} with shape {weight.shape}")
    if offset.ndim != 4:
        _bad("offset", "a 4-D (N, 2*deformable_groups*kh*kw, H_out, W_out) "
             "tensor", f"rank {offset.ndim} with shape {offset.shape}")
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    n, cin = int(x.shape[0]), int(x.shape[1])
    if cin % groups != 0:
        _bad("x", f"channel count divisible by groups={groups}",
             f"{cin} channels")
    if cin % deformable_groups != 0:
        _bad("x", f"channel count divisible by "
             f"deformable_groups={deformable_groups}", f"{cin} channels")
    if int(weight.shape[0]) % groups != 0:
        _bad("weight", f"output channels divisible by groups={groups}",
             f"{int(weight.shape[0])} output channels")
    if int(weight.shape[1]) * groups != cin:
        _bad("weight", f"shape[1] == C_in/groups = {cin // groups} "
             f"(C_in={cin}, groups={groups})", f"shape[1] == {int(weight.shape[1])}")
    if int(offset.shape[0]) != n:
        _bad("offset", f"batch size {n} (matching x)",
             f"batch size {int(offset.shape[0])}")
    off_c = 2 * deformable_groups * kh * kw
    if int(offset.shape[1]) != off_c:
        _bad("offset", f"shape[1] == 2 * deformable_groups * kh * kw = "
             f"{off_c} (deformable_groups={deformable_groups}, "
             f"kernel={kh}x{kw})", f"shape[1] == {int(offset.shape[1])}")
    hp = int(x.shape[2]) + 2 * padding[0]
    wp = int(x.shape[3]) + 2 * padding[1]
    out_hw = ((hp - (dilation[0] * (kh - 1) + 1)) // stride[0] + 1,
              (wp - (dilation[1] * (kw - 1) + 1)) // stride[1] + 1)
    if tuple(int(s) for s in offset.shape[2:]) != out_hw:
        _bad("offset", f"spatial shape {list(out_hw)} (the conv output "
             f"H_out x W_out)", f"spatial shape {[int(s) for s in offset.shape[2:]]}")
    if mask is not None:
        mask = ensure_tensor(mask)
        m_c = deformable_groups * kh * kw
        m_want = (n, m_c) + out_hw
        if (mask.ndim != 4
                or tuple(int(s) for s in mask.shape) != m_want):
            _bad("mask", f"shape {list(m_want)} "
                 f"(N, deformable_groups*kh*kw, H_out, W_out)",
                 f"shape {[int(s) for s in mask.shape]}")

    def f(inp, off, w, *rest):
        msk = rest[0] if mask is not None else None
        b = rest[-1] if bias is not None else None
        n, cin, h, wid = inp.shape
        inp_p = jnp.pad(inp, ((0, 0), (0, 0), (padding[0], padding[0]),
                              (padding[1], padding[1])))
        hp, wp = inp_p.shape[2], inp_p.shape[3]
        out_h = (hp - (dilation[0] * (kh - 1) + 1)) // stride[0] + 1
        out_w = (wp - (dilation[1] * (kw - 1) + 1)) // stride[1] + 1
        # base sampling positions (out_h, out_w, kh, kw)
        oy = jnp.arange(out_h) * stride[0]
        ox = jnp.arange(out_w) * stride[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        off = off.reshape(n, deformable_groups, kh * kw, 2, out_h, out_w)
        cg = cin // deformable_groups

        def per_image(img, o, m):
            cols = []
            for g in range(deformable_groups):
                dy = o[g, :, 0].transpose(1, 2, 0).reshape(out_h, out_w, kh, kw)
                dx = o[g, :, 1].transpose(1, 2, 0).reshape(out_h, out_w, kh, kw)
                ys = base_y + dy
                xs = base_x + dx
                sub = img[g * cg:(g + 1) * cg]
                sampled = _bilinear_sample(sub, ys, xs)  # (cg,oh,ow,kh,kw)
                if m is not None:
                    mm = m[g].transpose(1, 2, 0).reshape(out_h, out_w, kh, kw)
                    sampled = sampled * mm[None]
                cols.append(sampled)
            return jnp.concatenate(cols, axis=0)  # (cin,oh,ow,kh,kw)

        if msk is not None:
            msk = msk.reshape(n, deformable_groups, kh * kw, out_h, out_w)
            cols = jax.vmap(per_image)(inp_p, off, msk)
        else:
            cols = jax.vmap(lambda i, o: per_image(i, o, None))(inp_p, off)
        # conv as tensordot: w (cout, cin/groups, kh, kw)
        cout = w.shape[0]
        if groups == 1:
            out = jnp.einsum("nchwyx,ocyx->nohw", cols, w)
        else:
            cpg_in = cin // groups
            cpg_out = cout // groups
            outs = []
            for g in range(groups):
                outs.append(jnp.einsum(
                    "nchwyx,ocyx->nohw",
                    cols[:, g * cpg_in:(g + 1) * cpg_in],
                    w[g * cpg_out:(g + 1) * cpg_out]))
            out = jnp.concatenate(outs, axis=1)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(ensure_tensor(mask))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply("deform_conv2d", f, *args)


class DeformConv2D(Layer):
    """paddle.vision.ops.DeformConv2D parity (v2 when a mask is passed)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        from ..nn.initializer import XavierUniform
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *k), attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, mask=mask,
                             **self._cfg)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._size, self._scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._size, self._scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._size, self._scale)


# ---------------------------------------------------------------------------
# SSD / YOLO / RPN detection ops (reference: python/paddle/vision/ops.py
# prior_box/yolo_box/yolo_loss/matrix_nms/generate_proposals/
# distribute_fpn_proposals). All static-shape: candidate sets are padded to
# fixed sizes with validity encoded in scores/labels, the TPU-friendly form.
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes for one feature map. Returns (boxes, vars)
    of shape (H, W, num_priors, 4)."""
    import numpy as np

    input, image = ensure_tensor(input), ensure_tensor(image)
    h, w = int(input._data.shape[2]), int(input._data.shape[3])
    img_h, img_w = int(image._data.shape[2]), int(image._data.shape[3])
    step_h = steps[1] or img_h / h
    step_w = steps[0] or img_w / w

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []  # (box_w, box_h) per prior, per min_size
    for i, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            # Caffe-SSD order: min(ar=1), sqrt(min*max), then the other ars —
            # the order pretrained SSD heads were trained against
            whs.append((ms, ms))
            if max_sizes:
                mm = (ms * float(max_sizes[i])) ** 0.5
                whs.append((mm, mm))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        else:  # default order: all aspect ratios, then sqrt(min*max)
            for ar in ars:
                whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
            if max_sizes:
                mm = (ms * float(max_sizes[i])) ** 0.5
                whs.append((mm, mm))
    whs_np = np.asarray(whs, np.float32)  # (P, 2)

    cy = (np.arange(h, dtype=np.float32) + offset) * step_h
    cx = (np.arange(w, dtype=np.float32) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)  # (H, W)
    centers = np.stack([cxg, cyg], axis=-1)[:, :, None, :]  # (H, W, 1, 2)
    half = whs_np[None, None, :, :] / 2.0
    mins = (centers - half) / np.asarray([img_w, img_h], np.float32)
    maxs = (centers + half) / np.asarray([img_w, img_h], np.float32)
    boxes = np.concatenate([mins, maxs], axis=-1).astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.broadcast_to(np.asarray(variance, np.float32),
                            boxes.shape).copy()
    from ..core.tensor import to_tensor
    return to_tensor(jnp.asarray(boxes)), to_tensor(jnp.asarray(vars_))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head (B, A*(5+C), H, W) into boxes and scores.

    Returns (boxes (B, A*H*W, 4) xyxy in image pixels, scores
    (B, A*H*W, C)); predictions under ``conf_thresh`` get zero scores.
    """
    import numpy as np

    x, img_size = ensure_tensor(x), ensure_tensor(img_size)
    na = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(na, 2)
    b, ch, h, w = (int(s) for s in x._data.shape)
    attrs = 5 + class_num

    def fn(feat, imsz):
        if iou_aware:
            # layout: [na IoU channels block][na*(5+C) yolo block]
            ioup = jax.nn.sigmoid(feat[:, :na])[:, :, None]  # (B, A, 1, H, W)
            f = feat[:, na:].reshape(b, na, attrs, h, w)
        else:
            f = feat.reshape(b, na, attrs, h, w)
        gx = (jnp.arange(w, dtype=jnp.float32))[None, None, None, :]
        gy = (jnp.arange(h, dtype=jnp.float32))[None, None, :, None]
        sx = jax.nn.sigmoid(f[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = jax.nn.sigmoid(f[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        bx = (gx + sx) / w
        by = (gy + sy) / h
        input_w, input_h = w * downsample_ratio, h * downsample_ratio
        bw = jnp.exp(f[:, :, 2]) * anc[None, :, 0, None, None] / input_w
        bh = jnp.exp(f[:, :, 3]) * anc[None, :, 1, None, None] / input_h
        obj = jax.nn.sigmoid(f[:, :, 4])
        if iou_aware:
            iou_s = ioup[:, :, 0]
            obj = obj ** (1 - iou_aware_factor) * iou_s ** iou_aware_factor
        cls = jax.nn.sigmoid(f[:, :, 5:])  # (B, A, C, H, W)
        score = obj[:, :, None] * cls
        score = jnp.where(score > conf_thresh, score, 0.0)
        imh = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0.0, imw - 1)
            y1 = jnp.clip(y1, 0.0, imh - 1)
            x2 = jnp.clip(x2, 0.0, imw - 1)
            y2 = jnp.clip(y2, 0.0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(b, -1, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(b, -1, class_num)
        return boxes, scores

    out = apply("yolo_box", fn, x, img_size, differentiable=False)
    return tuple(out)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss for one detection head.

    x: (B, A*(5+C), H, W); gt_box: (B, G, 4) xywh in [0,1] image coords;
    gt_label: (B, G). Returns per-image loss (B,). Anchor assignment (best
    IoU over the FULL anchor set, masked to this head) and the
    ignore-high-IoU objectness rule follow the reference kernel.
    """
    import numpy as np

    x, gt_box, gt_label = (ensure_tensor(x), ensure_tensor(gt_box),
                           ensure_tensor(gt_label))
    extras = [ensure_tensor(gt_score)] if gt_score is not None else []
    all_anc = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    na = len(mask)
    b, ch, h, w = (int(s) for s in x._data.shape)
    attrs = 5 + class_num
    input_w = w * downsample_ratio
    input_h = h * downsample_ratio
    anc_this = all_anc[mask]  # (A, 2) pixels

    def fn(feat, gtb, gtl, *gs):
        f = feat.reshape(b, na, attrs, h, w)
        tx, ty = f[:, :, 0], f[:, :, 1]
        tw, th = f[:, :, 2], f[:, :, 3]
        tobj, tcls = f[:, :, 4], f[:, :, 5:]

        # --- decode predicted boxes (normalized) for the ignore mask
        gxx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gyy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        px = (gxx + jax.nn.sigmoid(tx)) / w
        py = (gyy + jax.nn.sigmoid(ty)) / h
        pw = jnp.exp(tw) * anc_this[None, :, 0, None, None] / input_w
        ph = jnp.exp(th) * anc_this[None, :, 1, None, None] / input_h

        gx, gy = gtb[..., 0], gtb[..., 1]          # (B, G)
        gw, gh = gtb[..., 2], gtb[..., 3]
        valid = (gw > 1e-8) & (gh > 1e-8)

        # IoU of every pred box vs every gt (xywh, normalized)
        def iou(px1, py1, pw1, ph1, qx, qy, qw, qh):
            l1, r1 = px1 - pw1 / 2, px1 + pw1 / 2
            t1, b1 = py1 - ph1 / 2, py1 + ph1 / 2
            l2, r2 = qx - qw / 2, qx + qw / 2
            t2, b2 = qy - qh / 2, qy + qh / 2
            iw = jnp.clip(jnp.minimum(r1, r2) - jnp.maximum(l1, l2), 0)
            ih = jnp.clip(jnp.minimum(b1, b2) - jnp.maximum(t1, t2), 0)
            inter = iw * ih
            return inter / (pw1 * ph1 + qw * qh - inter + 1e-10)

        pious = iou(px[..., None], py[..., None], pw[..., None],
                    ph[..., None],
                    gx[:, None, None, None, :], gy[:, None, None, None, :],
                    gw[:, None, None, None, :], gh[:, None, None, None, :])
        pious = jnp.where(valid[:, None, None, None, :], pious, 0.0)
        best_iou = jnp.max(pious, axis=-1)         # (B, A, H, W)
        ignore = best_iou > ignore_thresh

        # --- anchor assignment per gt: best shape-IoU over ALL anchors
        aw = all_anc[:, 0] / input_w
        ah = all_anc[:, 1] / input_h
        inter = (jnp.minimum(gw[..., None], aw[None, None]) *
                 jnp.minimum(gh[..., None], ah[None, None]))
        shape_iou = inter / (gw[..., None] * gh[..., None] +
                             aw[None, None] * ah[None, None] - inter + 1e-10)
        best_anchor = jnp.argmax(shape_iou, axis=-1)  # (B, G) in full set
        # position in this head's mask (or -1)
        mask_arr = jnp.asarray(mask)
        in_head = (best_anchor[..., None] == mask_arr[None, None]).astype(
            jnp.int32)
        head_slot = jnp.argmax(in_head, axis=-1)    # (B, G)
        assigned = (jnp.sum(in_head, axis=-1) > 0) & valid

        gi = jnp.clip((gx * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gy * h).astype(jnp.int32), 0, h - 1)

        # gather predictions at assigned cells: flat index per gt
        flat = (head_slot * h + gj) * w + gi        # (B, G)

        def gather_bg(t):  # t: (B, A, H, W) -> (B, G)
            tf = t.reshape(b, -1)
            return jnp.take_along_axis(tf, flat, axis=1)

        s_tx, s_ty = gather_bg(tx), gather_bg(ty)
        s_tw, s_th = gather_bg(tw), gather_bg(th)

        # targets
        tgt_x = gx * w - gi
        tgt_y = gy * h - gj
        aw_s = jnp.take(aw, jnp.clip(best_anchor, 0, all_anc.shape[0] - 1))
        ah_s = jnp.take(ah, jnp.clip(best_anchor, 0, all_anc.shape[0] - 1))
        tgt_w = jnp.log(jnp.clip(gw / jnp.clip(aw_s, 1e-10), 1e-10, None))
        tgt_h = jnp.log(jnp.clip(gh / jnp.clip(ah_s, 1e-10), 1e-10, None))
        box_scale = 2.0 - gw * gh                   # small boxes weigh more
        score_w = gs[0] if gs else jnp.ones_like(gx)
        wgt = jnp.where(assigned, box_scale * score_w, 0.0)

        def bce(logit, target):
            return jax.nn.softplus(logit) - logit * target

        loss_xy = (bce(s_tx, tgt_x) + bce(s_ty, tgt_y)) * wgt
        loss_wh = (jnp.abs(s_tw - tgt_w) + jnp.abs(s_th - tgt_h)) * wgt

        # objectness: positive target is the gt score (mixup support) at
        # assigned cells, negatives elsewhere unless ignored
        pos = jnp.zeros((b, na * h * w))
        pos = jax.vmap(lambda pz, fl, tgt: pz.at[fl].max(tgt))(
            pos, flat, jnp.where(assigned, score_w, 0.0))
        pos = pos.reshape(b, na, h, w)
        obj_w = jnp.where(pos > 0, 1.0, jnp.where(ignore, 0.0, 1.0))
        loss_obj = bce(tobj, pos) * obj_w

        # classification at assigned cells; reference smooth_weight is
        # min(1/C, 1/40): positive target 1-sw, negative sw
        sw = min(1.0 / class_num, 1.0 / 40.0) \
            if use_label_smooth and class_num > 1 else 0.0
        onehot = jax.nn.one_hot(gtl.astype(jnp.int32), class_num)
        onehot = onehot * (1.0 - sw) + (1.0 - onehot) * sw

        def gather_cls(t):  # (B, A, C, H, W) -> (B, G, C)
            tf = jnp.moveaxis(t, 2, -1).reshape(b, -1, class_num)
            return jnp.take_along_axis(
                tf, flat[..., None].astype(jnp.int32), axis=1)

        s_cls = gather_cls(tcls)
        loss_cls = jnp.sum(bce(s_cls, onehot), axis=-1) * \
            jnp.where(assigned, score_w, 0.0)

        per_img = (jnp.sum(loss_xy, axis=1) + jnp.sum(loss_wh, axis=1) +
                   jnp.sum(loss_obj, axis=(1, 2, 3)) +
                   jnp.sum(loss_cls, axis=1))
        return per_img

    return apply("yolo_loss", fn, x, gt_box, gt_label, *extras)


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): soft score decay from the pairwise IoU matrix —
    one dense (k, k) computation, no sequential suppression loop (ideal for
    the MXU). bboxes: (B, N, 4); scores: (B, C, N)."""
    bboxes, scores = ensure_tensor(bboxes), ensure_tensor(scores)

    def fn(bx, sc):
        bsz, n, _ = bx.shape
        c = sc.shape[1]

        def one(boxes, scores_cn):
            if 0 <= background_label < c:
                scores_cn = scores_cn.at[background_label].set(0.0)
            flat_s = scores_cn.reshape(-1)
            labels = jnp.repeat(jnp.arange(c), n)
            box_idx = jnp.tile(jnp.arange(n), c)
            flat_s = jnp.where(flat_s > score_threshold, flat_s, 0.0)
            k = min(nms_top_k, flat_s.shape[0])
            order = jnp.argsort(-flat_s)[:k]
            s_k = flat_s[order]
            l_k = labels[order]
            b_k = boxes[box_idx[order]]
            # pairwise IoU over the candidate set
            x1, y1, x2, y2 = b_k[:, 0], b_k[:, 1], b_k[:, 2], b_k[:, 3]
            off = 0.0 if normalized else 1.0
            area = jnp.clip(x2 - x1 + off, 0) * jnp.clip(y2 - y1 + off, 0)
            iw = jnp.clip(jnp.minimum(x2[:, None], x2[None]) -
                          jnp.maximum(x1[:, None], x1[None]) + off, 0)
            ih = jnp.clip(jnp.minimum(y2[:, None], y2[None]) -
                          jnp.maximum(y1[:, None], y1[None]) + off, 0)
            inter = iw * ih
            iou = inter / (area[:, None] + area[None] - inter + 1e-10)
            same = (l_k[:, None] == l_k[None]).astype(iou.dtype)
            # decay from every HIGHER-scored box of the same class
            upper = jnp.triu(jnp.ones_like(iou), 1).T  # [i, j]: j before i
            ious = iou * same * upper
            max_iou = jnp.max(ious, axis=1)
            if use_gaussian:
                # decay_ij = exp(-(iou_ij^2 - compensate_j^2)/sigma), where
                # compensate_j is box j's own max-IoU with its predecessors
                decay = jnp.where(jnp.any(ious > 0, axis=1),
                                  jnp.min(jnp.where(
                                      ious > 0,
                                      jnp.exp(-(ious ** 2 -
                                                max_iou[None, :] ** 2) /
                                              gaussian_sigma), 1.0), axis=1),
                                  1.0)
            else:
                decay = jnp.where(
                    jnp.any(ious > 0, axis=1),
                    jnp.min(jnp.where(ious > 0,
                                      (1 - ious) / (1 - max_iou[None, :]),
                                      1.0), axis=1), 1.0)
            new_s = s_k * decay
            new_s = jnp.where(new_s > post_threshold, new_s, 0.0)
            kk = min(keep_top_k, new_s.shape[0])
            fin = jnp.argsort(-new_s)[:kk]
            out_s = new_s[fin]
            out = jnp.concatenate([
                jnp.where(out_s > 0, l_k[fin], -1).astype(
                    jnp.float32)[:, None],
                out_s[:, None], b_k[fin]], axis=-1)
            idx = jnp.where(out_s > 0, box_idx[order][fin], -1)
            return out, idx, jnp.sum(out_s > 0).astype(jnp.int32)

        return jax.vmap(one)(bx, sc)

    out, idx, nums = apply("matrix_nms", fn, bboxes, scores,
                           differentiable=False)
    res = [out]
    if return_index:
        res.append(idx)
    if return_rois_num:
        res.append(nums)
    return tuple(res) if len(res) > 1 else res[0]


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation: decode deltas on anchors, clip to image,
    drop tiny boxes (zero-scored, shapes stay static), NMS, keep top-N.

    scores: (B, A, H, W); bbox_deltas: (B, 4A, H, W); anchors/variances:
    (H, W, A, 4) or (H*W*A, 4). Returns (rois (B, post_nms_top_n, 4),
    roi_probs (B, post_nms_top_n, 1)[, rois_num (B,)]).
    """
    from ..ops.vision import _nms_suppress

    scores, bbox_deltas = ensure_tensor(scores), ensure_tensor(bbox_deltas)
    img_size, anchors = ensure_tensor(img_size), ensure_tensor(anchors)
    variances = ensure_tensor(variances)
    off = 1.0 if pixel_offset else 0.0

    def fn(sc, bd, imsz, anc, var):
        bsz, a, h, w = sc.shape
        n = a * h * w
        anc_f = anc.reshape(-1, 4)
        var_f = var.reshape(-1, 4)

        def one(s, d, sz):
            s_f = s.reshape(-1)                           # A*H*W (A major)
            # deltas (4A, H, W) -> (A, 4, H, W) -> (A, H, W, 4) -> flat
            d_f = jnp.moveaxis(d.reshape(a, 4, h, w), 1, -1).reshape(-1, 4)
            # anchors come (H, W, A, 4); reorder flat index to A-major
            anc_hw = anc_f.reshape(h, w, a, 4) if anc_f.shape[0] == n else None
            if anc_hw is not None:
                anc_am = jnp.moveaxis(anc_hw, 2, 0).reshape(-1, 4)
                var_am = jnp.moveaxis(var_f.reshape(h, w, a, 4), 2,
                                      0).reshape(-1, 4)
            else:
                anc_am, var_am = anc_f, var_f
            aw = anc_am[:, 2] - anc_am[:, 0] + off
            ah = anc_am[:, 3] - anc_am[:, 1] + off
            acx = anc_am[:, 0] + aw * 0.5
            acy = anc_am[:, 1] + ah * 0.5
            cx = var_am[:, 0] * d_f[:, 0] * aw + acx
            cy = var_am[:, 1] * d_f[:, 1] * ah + acy
            bw = jnp.exp(jnp.clip(var_am[:, 2] * d_f[:, 2], None,
                                  10.0)) * aw
            bh = jnp.exp(jnp.clip(var_am[:, 3] * d_f[:, 3], None,
                                  10.0)) * ah
            x1 = jnp.clip(cx - bw * 0.5, 0, sz[1] - off)
            y1 = jnp.clip(cy - bh * 0.5, 0, sz[0] - off)
            x2 = jnp.clip(cx + bw * 0.5 - off, 0, sz[1] - off)
            y2 = jnp.clip(cy + bh * 0.5 - off, 0, sz[0] - off)
            boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
            keep_size = ((x2 - x1 + off) >= min_size) & \
                        ((y2 - y1 + off) >= min_size)
            s_v = jnp.where(keep_size, s_f, 0.0)
            k = min(pre_nms_top_n, n)
            order = jnp.argsort(-s_v)[:k]
            bs, ss = boxes[order], s_v[order]
            keep = _nms_suppress(bs, nms_thresh) & (ss > 0)
            ss = jnp.where(keep, ss, 0.0)
            kk = min(post_nms_top_n, k)
            fin = jnp.argsort(-ss)[:kk]
            out_b, out_s = bs[fin], ss[fin]
            if kk < post_nms_top_n:
                pad = post_nms_top_n - kk
                out_b = jnp.pad(out_b, ((0, pad), (0, 0)))
                out_s = jnp.pad(out_s, (0, pad))
            return out_b, out_s[:, None], jnp.sum(out_s > 0).astype(jnp.int32)

        return jax.vmap(one)(sc, bd, imsz)

    rois, probs, nums = apply("generate_proposals", fn, scores, bbox_deltas,
                              img_size, anchors, variances,
                              differentiable=False)
    if return_rois_num:
        return rois, probs, nums
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale (eager, data-dependent sizes —
    documented divergence: raises under tracing like other dynamic-shape
    ops). Returns (multi_rois list, restore_index[, rois_num_per_level])."""
    import numpy as np

    from ..core.tensor import to_tensor

    fpn_rois = ensure_tensor(fpn_rois)
    rois = np.asarray(fpn_rois._data)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.clip((rois[:, 2] - rois[:, 0] + off), 0, None) *
                    np.clip((rois[:, 3] - rois[:, 1] + off), 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois, restore, nums = [], [], []
    order = []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        multi_rois.append(to_tensor(jnp.asarray(rois[idx])))
        nums.append(len(idx))
        order.append(idx)
    order_all = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore_index = np.empty_like(order_all)
    restore_index[order_all] = np.arange(order_all.shape[0])
    restore_t = to_tensor(jnp.asarray(restore_index.reshape(-1, 1)))
    if rois_num is not None:
        return multi_rois, restore_t, [
            to_tensor(jnp.asarray(np.asarray([nv], np.int32)))
            for nv in nums]
    return multi_rois, restore_t


__all__ += ["prior_box", "yolo_box", "yolo_loss", "matrix_nms",
            "generate_proposals", "distribute_fpn_proposals"]


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds (reference: paddle.vision.ops /
    fluid box_clip op): input (..., 4) [xmin, ymin, xmax, ymax], im_info
    per-image (H, W, scale) — boxes clamp to [0, W-1] x [0, H-1] after
    scale."""
    from ..core.tensor import apply

    input, im_info = ensure_tensor(input), ensure_tensor(im_info)

    def f(boxes, info):
        info = info.reshape(-1)
        h, w = info[0], info[1]
        scale = info[2] if info.shape[0] > 2 else jnp.asarray(1.0, info.dtype)
        wmax = w / scale - 1.0
        hmax = h / scale - 1.0
        x1 = jnp.clip(boxes[..., 0], 0.0, wmax)
        y1 = jnp.clip(boxes[..., 1], 0.0, hmax)
        x2 = jnp.clip(boxes[..., 2], 0.0, wmax)
        y2 = jnp.clip(boxes[..., 3], 0.0, hmax)
        return jnp.stack([x1, y1, x2, y2], axis=-1)

    return apply("box_clip", f, input, im_info)


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy bipartite matching (reference: the SSD target-assign
    bipartite_match op): dist (N, M) similarity; each column matches at
    most one row. ``match_type='per_prediction'`` additionally matches
    unmatched columns to their best row when the distance exceeds
    ``dist_threshold``. Returns (match_indices (1, M) int32 with -1 for
    unmatched, match_dist (1, M)). Host-side numpy loop (data-prep op,
    like the reference's CPU-only kernel)."""
    import numpy as np

    from ..core.tensor import Tensor

    d = np.array(dist_matrix.numpy() if hasattr(dist_matrix, "numpy")
                 else dist_matrix, np.float32)
    if d.ndim != 2:
        raise ValueError("bipartite_match expects a 2-D distance matrix")
    n, m = d.shape
    idx = np.full((m,), -1, np.int32)
    dist = np.zeros((m,), np.float32)
    # mask with NaN (not -inf): real -inf entries stay distinguishable
    # from consumed rows/columns, and NaN distances are never matched
    work = d.copy()
    work[~np.isfinite(work)] = np.nan
    for _ in range(min(n, m)):
        if not np.any(~np.isnan(work)):
            break
        r, c = np.unravel_index(np.nanargmax(work), work.shape)
        idx[c] = r
        dist[c] = d[r, c]
        work[r, :] = np.nan
        work[:, c] = np.nan
    if match_type == "per_prediction":
        for c in range(m):
            if idx[c] == -1:
                r = int(np.argmax(d[:, c]))
                if d[r, c] >= dist_threshold:
                    idx[c] = r
                    dist[c] = d[r, c]
    return (Tensor(jnp.asarray(idx[None])),
            Tensor(jnp.asarray(dist[None])))


__all__ += ["box_clip", "bipartite_match"]
