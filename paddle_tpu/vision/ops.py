"""``paddle.vision.ops`` (reference: python/paddle/vision/ops.py — roi_align,
roi_pool, nms, box ops, DeformConv2D, PSRoIPool).

TPU-native notes: ROI ops are static-shape gathers (bilinear sample grids
computed per-box with fixed output resolution — XLA-friendly, no dynamic
shapes); deformable conv samples the input at learned offsets via the same
bilinear gather; NMS reuses the padded fixed-iteration kernel from
ops/vision.py (the detection-op layer built for PP-YOLOE).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import apply
from ..nn.layer import Layer
from ..ops._helpers import ensure_tensor
from ..ops.vision import bbox_iou, box_area, multiclass_nms, nms  # noqa: F401

__all__ = ["roi_align", "roi_pool", "nms", "box_area", "bbox_iou",
           "box_coder", "DeformConv2D", "deform_conv2d", "RoIAlign",
           "RoIPool", "PSRoIPool", "psroi_pool"]


def _bilinear_sample(feat, ys, xs):
    """feat (C, H, W); ys/xs arbitrary same-shaped grids → (C, *grid)."""
    h, w = feat.shape[-2:]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yy = jnp.clip(y0 + dy, 0, h - 1).astype(jnp.int32)
            xx = jnp.clip(x0 + dx, 0, w - 1).astype(jnp.int32)
            # out-of-range taps contribute zero (exact torchvision/paddle
            # boundary semantics)
            valid = ((y0 + dy >= 0) & (y0 + dy <= h - 1) &
                     (x0 + dx >= 0) & (x0 + dx <= w - 1))
            tap = feat[:, yy, xx]
            out = out + tap * (wy * wx * valid)[None]
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True, name=None):
    """ROI Align (reference: phi::RoiAlignKernel). ``x`` (N,C,H,W); ``boxes``
    (R,4) x1y1x2y2 in input coords; ``boxes_num`` (N,) rois per image."""
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n = feat.shape[0]
        r = rois.shape[0]
        # map each roi to its batch image: repeat image ids by rois_num
        ends = jnp.cumsum(rois_num)
        img_id = jnp.sum(jnp.arange(r)[:, None] >= ends[None, :], axis=1)
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        s = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid per roi: (ph*s, pw*s) points, averaged per bin
        gy = (jnp.arange(ph * s) + 0.5) / s  # in bin units
        gx = (jnp.arange(pw * s) + 0.5) / s

        def one(roi_idx):
            fy = y1[roi_idx] + gy * bin_h[roi_idx]      # (ph*s,)
            fx = x1[roi_idx] + gx * bin_w[roi_idx]      # (pw*s,)
            ys = jnp.broadcast_to(fy[:, None], (ph * s, pw * s))
            xs = jnp.broadcast_to(fx[None, :], (ph * s, pw * s))
            sampled = _bilinear_sample(feat[img_id[roi_idx]], ys, xs)
            c = sampled.shape[0]
            sampled = sampled.reshape(c, ph, s, pw, s)
            return sampled.mean(axis=(2, 4))  # (C, ph, pw)

        return jax.vmap(one)(jnp.arange(r))

    return apply("roi_align", f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None):
    """ROI max-pool (reference: phi::RoiPoolKernel)."""
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        ends = jnp.cumsum(rois_num)
        img_id = jnp.sum(jnp.arange(r)[:, None] >= ends[None, :], axis=1)
        x1 = jnp.round(rois[:, 0] * spatial_scale)
        y1 = jnp.round(rois[:, 1] * spatial_scale)
        x2 = jnp.round(rois[:, 2] * spatial_scale)
        y2 = jnp.round(rois[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)

        ys_all = jnp.arange(h, dtype=jnp.float32)
        xs_all = jnp.arange(w, dtype=jnp.float32)

        def one(roi_idx):
            bin_h = rh[roi_idx] / ph
            bin_w = rw[roi_idx] / pw
            ys0 = y1[roi_idx] + jnp.arange(ph) * bin_h
            xs0 = x1[roi_idx] + jnp.arange(pw) * bin_w
            # membership mask per bin over the full H/W (static shapes)
            ymask = ((ys_all[None, :] >= jnp.floor(ys0)[:, None]) &
                     (ys_all[None, :] < jnp.ceil(ys0 + bin_h)[:, None]))
            xmask = ((xs_all[None, :] >= jnp.floor(xs0)[:, None]) &
                     (xs_all[None, :] < jnp.ceil(xs0 + bin_w)[:, None]))
            m = (ymask[:, None, :, None] & xmask[None, :, None, :])
            fimg = feat[img_id[roi_idx]]  # (C,H,W)
            big = jnp.where(m[None], fimg[:, None, None, :, :], -jnp.inf)
            return big.max(axis=(-1, -2))  # (C, ph, pw)

        return jax.vmap(one)(jnp.arange(r))

    return apply("roi_pool", f, x, boxes, boxes_num)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
               name=None):
    """Position-sensitive ROI pooling (reference: phi::PsroiPoolKernel):
    channel group (i,j) feeds output bin (i,j), average-pooled."""
    x = ensure_tensor(x)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    c = int(x.shape[1])
    if c % (ph * pw) != 0:
        raise ValueError(f"channels {c} must be divisible by "
                         f"output_size^2 {ph * pw}")
    out_c = c // (ph * pw)
    aligned = roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                        sampling_ratio=2, aligned=False)

    def f(a):
        r = a.shape[0]
        # paddle channel layout: input channel (c*ph + i)*pw + j feeds output
        # channel c at bin (i, j)
        blocks = a.reshape(r, out_c, ph, pw, ph, pw)
        ii = jnp.arange(ph)[:, None]
        jj = jnp.arange(pw)[None, :]
        return blocks[:, :, ii, jj, ii, jj]

    return apply("psroi_pool", f, aligned)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0, name=None):
    """Encode/decode boxes against priors (reference: phi::BoxCoderKernel)."""
    prior = ensure_tensor(prior_box)
    tb = ensure_tensor(target_box)
    pbv = None if prior_box_var is None else ensure_tensor(prior_box_var)
    norm = 0.0 if box_normalized else 1.0

    def f(p, t, *maybe_var):
        var = maybe_var[0] if maybe_var else jnp.ones_like(p)
        pw = p[..., 2] - p[..., 0] + norm
        ph_ = p[..., 3] - p[..., 1] + norm
        pcx = p[..., 0] + pw * 0.5
        pcy = p[..., 1] + ph_ * 0.5
        if code_type == "encode_center_size":
            tw = t[..., 2] - t[..., 0] + norm
            th = t[..., 3] - t[..., 1] + norm
            tcx = t[..., 0] + tw * 0.5
            tcy = t[..., 1] + th * 0.5
            out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph_,
                             jnp.log(tw / pw), jnp.log(th / ph_)], axis=-1)
            return out / var
        # decode
        d = t * var
        cx = d[..., 0] * pw + pcx
        cy = d[..., 1] * ph_ + pcy
        w = jnp.exp(d[..., 2]) * pw
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)

    if pbv is not None:
        return apply("box_coder", f, prior, tb, pbv)
    return apply("box_coder", f, prior, tb)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference: phi::DeformableConvKernel): bilinear
    sampling at offset-shifted taps, then a dense matmul per output pixel."""
    x = ensure_tensor(x)
    offset = ensure_tensor(offset)
    weight = ensure_tensor(weight)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    kh, kw = int(weight.shape[2]), int(weight.shape[3])

    def f(inp, off, w, *rest):
        msk = rest[0] if mask is not None else None
        b = rest[-1] if bias is not None else None
        n, cin, h, wid = inp.shape
        inp_p = jnp.pad(inp, ((0, 0), (0, 0), (padding[0], padding[0]),
                              (padding[1], padding[1])))
        hp, wp = inp_p.shape[2], inp_p.shape[3]
        out_h = (hp - (dilation[0] * (kh - 1) + 1)) // stride[0] + 1
        out_w = (wp - (dilation[1] * (kw - 1) + 1)) // stride[1] + 1
        # base sampling positions (out_h, out_w, kh, kw)
        oy = jnp.arange(out_h) * stride[0]
        ox = jnp.arange(out_w) * stride[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        off = off.reshape(n, deformable_groups, kh * kw, 2, out_h, out_w)
        cg = cin // deformable_groups

        def per_image(img, o, m):
            cols = []
            for g in range(deformable_groups):
                dy = o[g, :, 0].transpose(1, 2, 0).reshape(out_h, out_w, kh, kw)
                dx = o[g, :, 1].transpose(1, 2, 0).reshape(out_h, out_w, kh, kw)
                ys = base_y + dy
                xs = base_x + dx
                sub = img[g * cg:(g + 1) * cg]
                sampled = _bilinear_sample(sub, ys, xs)  # (cg,oh,ow,kh,kw)
                if m is not None:
                    mm = m[g].transpose(1, 2, 0).reshape(out_h, out_w, kh, kw)
                    sampled = sampled * mm[None]
                cols.append(sampled)
            return jnp.concatenate(cols, axis=0)  # (cin,oh,ow,kh,kw)

        if msk is not None:
            msk = msk.reshape(n, deformable_groups, kh * kw, out_h, out_w)
            cols = jax.vmap(per_image)(inp_p, off, msk)
        else:
            cols = jax.vmap(lambda i, o: per_image(i, o, None))(inp_p, off)
        # conv as tensordot: w (cout, cin/groups, kh, kw)
        cout = w.shape[0]
        if groups == 1:
            out = jnp.einsum("nchwyx,ocyx->nohw", cols, w)
        else:
            cpg_in = cin // groups
            cpg_out = cout // groups
            outs = []
            for g in range(groups):
                outs.append(jnp.einsum(
                    "nchwyx,ocyx->nohw",
                    cols[:, g * cpg_in:(g + 1) * cpg_in],
                    w[g * cpg_out:(g + 1) * cpg_out]))
            out = jnp.concatenate(outs, axis=1)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(ensure_tensor(mask))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return apply("deform_conv2d", f, *args)


class DeformConv2D(Layer):
    """paddle.vision.ops.DeformConv2D parity (v2 when a mask is passed)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        from ..nn.initializer import XavierUniform
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *k), attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, mask=mask,
                             **self._cfg)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._size, self._scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._size, self._scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._size = output_size
        self._scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._size, self._scale)
