"""Global flags registry.

Capability parity with the reference's gflags-style ``FLAGS_*`` system
(upstream: paddle/common/flags.h, paddle/phi/core/flags.cc — settable via
``FLAGS_x=y`` env vars or ``paddle.set_flags``/``get_flags`` at runtime).
Here it is a plain Python registry: flags are declared with a type, default,
and help string; environment variables named ``FLAGS_<name>`` override the
default at first read; ``set_flags`` overrides at runtime.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional

__all__ = ["define_flag", "get_flags", "set_flags", "flag"]


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: lambda s: int(s, 0),
    float: float,
    str: str,
}


@dataclass
class _Flag:
    name: str
    type: type
    default: Any
    help: str
    value: Any = None
    env_checked: bool = False


class _FlagRegistry:
    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.Lock()

    def define(self, name: str, type_: type, default: Any, help_: str = "") -> None:
        name = self._canon(name)
        with self._lock:
            if name in self._flags:
                return
            self._flags[name] = _Flag(name, type_, default, help_)

    @staticmethod
    def _canon(name: str) -> str:
        return name if name.startswith("FLAGS_") else "FLAGS_" + name

    def get(self, name: str) -> Any:
        name = self._canon(name)
        with self._lock:
            f = self._flags.get(name)
            if f is None:
                raise KeyError(f"flag {name!r} is not defined")
            if f.value is not None:
                return f.value
            if not f.env_checked:
                f.env_checked = True
                env = os.environ.get(f.name)
                if env is not None:
                    f.value = _PARSERS.get(f.type, str)(env)
                    return f.value
            return f.default

    def set(self, name: str, value: Any) -> None:
        global _EPOCH
        name = self._canon(name)
        with self._lock:
            f = self._flags.get(name)
            if f is None:
                raise KeyError(f"flag {name!r} is not defined")
            f.value = f.type(value) if not isinstance(value, f.type) else value
            # runtime flag writes bump the epoch: caches keyed on flag-
            # dependent behavior (the eager dispatch cache bakes flag reads
            # like tpu_matmul_precision/flash_block_* into compiled entries
            # at trace time) include it in their keys, so a set_flags()
            # coarsely invalidates them instead of serving stale compiles
            _EPOCH += 1

    def names(self) -> Iterable[str]:
        with self._lock:
            return list(self._flags)


_registry = _FlagRegistry()

# monotone count of runtime flag writes (never of env-derived first reads);
# see _FlagRegistry.set for the invalidation contract
_EPOCH = 0


def epoch() -> int:
    """Current runtime-flag epoch (bumped by every ``set_flags`` write)."""
    return _EPOCH


def define_flag(name: str, default: Any, help: str = "", flag_type: Optional[type] = None) -> None:
    """Declare a flag (analogue of ``PHI_DEFINE_EXPORTED_*``)."""
    _registry.define(name, flag_type or type(default), default, help)


def flag(name: str) -> Any:
    """Read a single flag value."""
    return _registry.get(name)


def get_flags(names) -> Dict[str, Any]:
    """Parity with ``paddle.get_flags``: accepts a name or list of names."""
    if isinstance(names, str):
        names = [names]
    return {_FlagRegistry._canon(n): _registry.get(n) for n in names}


def set_flags(flags: Dict[str, Any]) -> None:
    """Parity with ``paddle.set_flags({'FLAGS_x': v})``."""
    for k, v in flags.items():
        _registry.set(k, v)


# --- core flags used across the framework -----------------------------------
define_flag("eager_op_jit", True, "jit-compile each eager op (per-op kernel cache)")
define_flag("to_static_capture_lowered", False,
            "capture arg specs on each compiled call so "
            "StaticFunction.compiled_text() can report the XLA HLO (debug)")
define_flag("check_nan_inf", False, "check every op output for nan/inf (debug)")
define_flag("amp_dtype", "bfloat16", "default autocast dtype on TPU")
define_flag("allocator_strategy", "auto_growth", "accepted for parity; XLA/PJRT manages memory")
define_flag("use_stream_safe_cuda_allocator", False, "parity no-op on TPU")
# fp32 matmuls run at full fp32 (paddle semantics). The MXU's native
# bf16xbf16->fp32 path is reached through bf16 dtypes / AMP, where this flag
# is irrelevant; lower it only to allow bf16-split passes for fp32 inputs.
define_flag("tpu_matmul_precision", "highest", "jax matmul precision: default|high|highest")
define_flag("q8_pallas_update", True,
            "route block-multiple int8-state Adam updates through the fused "
            "Pallas kernel on TPU (one kernel/param, zero HBM transients)")
define_flag("log_level", 0, "framework VLOG-style verbosity")
