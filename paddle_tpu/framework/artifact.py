"""Safe on-disk container for inference artifacts (.pdmodel).

Upstream's ``.pdmodel`` is a protobuf ProgramDesc; ours carries serialized
StableHLO. A pickle container would execute arbitrary code at load time and
silently masquerade as reference-compatible, so the format is explicit and
inert: magic line, little-endian u64 header length, JSON header, then raw
blob bytes back-to-back. Numpy arrays ride as ``.npy`` blobs and are loaded
with ``allow_pickle=False``.

Layout::

    PDTPU-ART\\n | u64 header_len | header JSON | blob 0 | blob 1 | ...

The header's ``blobs`` entry is ``[[name, nbytes], ...]`` in file order;
``arrays`` lists which blob names are npy-encoded.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, List, Tuple

import numpy as np

MAGIC = b"PDTPU-ART\n"

__all__ = ["MAGIC", "write_artifact", "read_artifact",
           "read_model_payload"]


def write_artifact(path: str, header: Dict[str, Any],
                   blobs: Dict[str, bytes] | None = None,
                   arrays: Dict[str, np.ndarray] | None = None) -> None:
    """Write ``header`` (JSON-serializable) plus named binary/array blobs."""
    blobs = dict(blobs or {})
    array_dtypes: Dict[str, str] = {}
    for name, arr in (arrays or {}).items():
        arr = np.ascontiguousarray(arr)
        # np.lib.format writes extension dtypes (ml_dtypes bfloat16/fp8) as
        # raw void ('|V2'); record the true dtype so read can view it back
        array_dtypes[name] = str(arr.dtype)
        buf = io.BytesIO()
        np.lib.format.write_array(buf, arr, allow_pickle=False)
        blobs[name] = buf.getvalue()
    hdr = dict(header)
    hdr["blobs"] = [[name, len(b)] for name, b in blobs.items()]
    hdr["arrays"] = array_dtypes
    hdr_bytes = json.dumps(hdr).encode()
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(len(hdr_bytes).to_bytes(8, "little"))
        f.write(hdr_bytes)
        for b in blobs.values():
            f.write(b)


def read_artifact(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Returns (header, blobs); npy-encoded blobs come back as ndarrays."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(
                f"{path} is not a paddle_tpu artifact (bad magic). Reference "
                "protobuf .pdmodel files and pre-v2 pickle artifacts cannot "
                "be loaded; re-export with this framework's save APIs.")
        hdr_len = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(hdr_len).decode())
        blobs: Dict[str, Any] = {}
        arrays_meta = header.get("arrays", [])
        if isinstance(arrays_meta, list):  # legacy: names only, no dtypes
            arrays_meta = {n: None for n in arrays_meta}
        for name, nbytes in header.get("blobs", []):
            raw = f.read(nbytes)
            if len(raw) != nbytes:
                raise ValueError(f"{path}: truncated blob {name!r}")
            if name in arrays_meta:
                arr = np.lib.format.read_array(io.BytesIO(raw),
                                               allow_pickle=False)
                want = arrays_meta[name]
                if want and str(arr.dtype) != want:
                    arr = arr.view(_lookup_dtype(want))
                blobs[name] = arr
            else:
                blobs[name] = raw
    return header, blobs


def _lookup_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 / float8 extension dtypes
        return np.dtype(getattr(ml_dtypes, name))


def read_model_payload(path: str) -> Dict[str, Any]:
    """Load a .pdmodel artifact into the flat payload dict the model loaders
    (jit.load, inference.Predictor, static.load_inference_model) consume:
    header fields plus ``stablehlo`` bytes and, for jit artifacts, ``state``
    (the ordered param arrays)."""
    header, blobs = read_artifact(path)
    payload = dict(header)
    payload["stablehlo"] = blobs.get("stablehlo")
    if "state_names" in header:
        payload["state"] = [blobs[f"state/{i}"]
                            for i in range(len(header["state_names"]))]
    return payload
