"""``paddle.framework.random`` — RNG state plumbing (reference:
python/paddle/framework/random.py). The TPU build has ONE splittable
generator (core/random.py); per-device CUDA states collapse onto it."""

from __future__ import annotations

from ..core.random import (  # noqa: F401
    default_generator, get_rng_state, seed, set_rng_state,
)


def get_cuda_rng_state():
    """Parity alias: there is no per-CUDA-device state; returns the global
    generator's state list."""
    return get_rng_state()


def set_cuda_rng_state(state) -> None:
    set_rng_state(state)


def get_random_seed_generator(name: str):
    return default_generator
