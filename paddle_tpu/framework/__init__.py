"""Framework utilities: save/load, in_dynamic_mode shims, ParamAttr re-export."""

from .io import save, load  # noqa: F401


def in_dynamic_mode() -> bool:
    """Parity: eager mode is the default; to_static traces are 'static'."""
    from ..core.tracing import trace_state
    return trace_state() is None


def in_pir_mode() -> bool:
    return False
