"""``paddle.save`` / ``paddle.load``.

Parity surface: python/paddle/framework/io.py — pickle of nested state
structures with tensors materialized to numpy (Place dropped on save,
restored to the current place on load). Compatible payloads: Layer
state_dicts, optimizer state_dicts, bare tensors, nested containers.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor, to_tensor


class _TensorPayload:
    """Pickle-stable wrapper marking arrays that were Tensors."""

    __slots__ = ("array",)

    def __init__(self, array: np.ndarray):
        self.array = array


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any) -> Any:
    if isinstance(obj, _TensorPayload):
        return to_tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, **configs) -> Any:
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))
