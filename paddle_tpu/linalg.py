"""``paddle.linalg`` namespace. Parity: python/paddle/linalg.py exports."""

import jax.numpy as jnp

from .core.tensor import apply
from .ops._helpers import ensure_tensor
from .ops.linalg import (  # noqa: F401
    matmul, bmm, dot, inner, outer, einsum, kron, mv, addmm, norm, dist,
    inv, pinv, det, slogdet, svd, qr, eigh, eig, eigvals, eigvalsh, cholesky,
    cholesky_inverse, cholesky_solve, solve, triangular_solve, lstsq, matrix_power, matrix_rank,
    cond, cov, corrcoef, multi_dot, cross, householder_product,
    vecdot, matrix_exp, lu, lu_unpack, ormqr,
)
from .ops.math_ext import cdist  # noqa: F401


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """Vector p-norm over ``axis`` (reference: paddle.linalg.vector_norm)."""
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def f(a):
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply("vector_norm", f, x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """Matrix norm over the two ``axis`` dims (fro / nuc / ±1 / ±2 / ±inf)."""
    x = ensure_tensor(x)
    ax = tuple(axis)

    def f(a):
        # move the two matrix dims last so jnp.linalg.norm sees (..., m, n)
        mvd = jnp.moveaxis(a, ax, (-2, -1))
        r = jnp.linalg.norm(mvd, ord=p, axis=(-2, -1))
        if keepdim:
            for d in sorted(d % a.ndim for d in ax):
                r = jnp.expand_dims(r, d)
        return r

    return apply("matrix_norm", f, x)
from .ops.math_ext2 import matrix_transpose, svdvals  # noqa: F401,E402


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference: paddle.linalg.svd_lowrank,
    Halko et al. 2011): returns (U, S, V) with q columns via subspace
    iteration — q matmuls instead of a full decomposition."""
    from .core.random import default_generator
    from .core.tensor import apply, Tensor
    import jax
    import jax.numpy as jnp

    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = default_generator.split_key()
    m, n = int(x._data.shape[-2]), int(x._data.shape[-1])
    q_eff = min(int(q), m, n)
    if M is not None and not isinstance(M, Tensor):
        M = Tensor(jnp.asarray(M))
    extras = [M] if M is not None else []

    def f(a, *rest):
        a32 = a.astype(jnp.float32)
        if rest:
            a32 = a32 - rest[0].astype(jnp.float32)
        omega = jax.random.normal(key, a32.shape[:-2] + (n, q_eff),
                                  jnp.float32)
        y = a32 @ omega
        for _ in range(int(niter)):
            y = a32 @ (a32.swapaxes(-1, -2) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = qmat.swapaxes(-1, -2) @ a32
        ub, s_, vt = jnp.linalg.svd(b, full_matrices=False)
        u = qmat @ ub
        return u.astype(a.dtype), s_.astype(a.dtype), \
            vt.swapaxes(-1, -2).astype(a.dtype)

    return apply("svd_lowrank", f, x, *extras)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (reference: paddle.linalg.pca_lowrank): returns
    (U, S, V) of the (optionally centered) data via svd_lowrank."""
    from .core.tensor import Tensor
    import jax.numpy as jnp

    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    m, n = int(x._data.shape[-2]), int(x._data.shape[-1])
    if q is None:
        q = min(6, m, n)
    if center:
        mean = x.mean(axis=-2, keepdim=True)
        x = x - mean
    return svd_lowrank(x, q=q, niter=niter)
