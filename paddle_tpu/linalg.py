"""``paddle.linalg`` namespace. Parity: python/paddle/linalg.py exports."""

from .ops.linalg import (  # noqa: F401
    matmul, bmm, dot, inner, outer, einsum, kron, mv, addmm, norm, dist,
    inv, pinv, det, slogdet, svd, qr, eigh, eig, eigvals, eigvalsh, cholesky,
    cholesky_solve, solve, triangular_solve, lstsq, matrix_power, matrix_rank,
    cond, cov, corrcoef, multi_dot, cross, householder_product,
)
vector_norm = norm
matrix_norm = norm
