"""``paddle.text``: NLP datasets (reference: python/paddle/text/datasets/ —
Imdb, Movielens, Conll05st, UCIHousing, WMT14/16).

Zero-egress build: parsing real corpus files is not implemented — every
dataset generates a deterministic synthetic sample set (label-correlated so
models can learn), the same hermetic pattern as paddle_tpu.vision.datasets.
Passing ``data_file`` warns loudly rather than silently substituting.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from .io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st", "Movielens", "ViterbiDecoder",
           "viterbi_decode"]


class Imdb(Dataset):
    """Binary sentiment dataset; synthetic corpus when no local data."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150):
        super().__init__()
        if data_file is not None:
            import warnings
            warnings.warn(
                f"{type(self).__name__}: parsing data_file is not "
                "implemented in this build; a deterministic SYNTHETIC "
                "dataset is used instead", stacklevel=2)
        self.mode = mode
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 2000 if mode == "train" else 500
        vocab = 5000
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        lengths = rng.integers(20, 200, n)
        self.docs: List[np.ndarray] = []
        self.labels = rng.integers(0, 2, n).astype(np.int64)
        for i in range(n):
            # label-correlated token distribution so models can learn
            lo = 0 if self.labels[i] == 0 else vocab // 2
            self.docs.append(rng.integers(
                lo, lo + vocab // 2, lengths[i]).astype(np.int64))

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    """Boston-housing-shaped regression set (13 features)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        super().__init__()
        if data_file is not None:
            import warnings
            warnings.warn(
                f"{type(self).__name__}: parsing data_file is not "
                "implemented in this build; a deterministic SYNTHETIC "
                "dataset is used instead", stacklevel=2)
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        self.x = rng.normal(size=(n, 13)).astype(np.float32)
        w = np.linspace(-1, 1, 13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.normal(size=n)).astype(
            np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Conll05st(Dataset):
    """SRL-shaped dataset: token/predicate/mark sequences + BIO labels."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 **kwargs):
        super().__init__()
        if data_file is not None:
            import warnings
            warnings.warn(
                f"{type(self).__name__}: parsing data_file is not "
                "implemented in this build; a deterministic SYNTHETIC "
                "dataset is used instead", stacklevel=2)
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 500 if mode == "train" else 100
        self.samples = []
        for _ in range(n):
            ln = int(rng.integers(5, 30))
            words = rng.integers(0, 5000, ln).astype(np.int64)
            pred = np.full(ln, rng.integers(0, 3000), np.int64)
            mark = (rng.random(ln) < 0.2).astype(np.int64)
            labels = rng.integers(0, 59, ln).astype(np.int64)
            self.samples.append((words, pred, mark, labels))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """Rating-prediction tuples (user, gender, age, job, movie, cat, rating)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 **kwargs):
        super().__init__()
        if data_file is not None:
            import warnings
            warnings.warn(
                f"{type(self).__name__}: parsing data_file is not "
                "implemented in this build; a deterministic SYNTHETIC "
                "dataset is used instead", stacklevel=2)
        rng = np.random.default_rng(0 if mode == "train" else 1)
        n = 3000 if mode == "train" else 600
        self.rows = []
        for _ in range(n):
            self.rows.append((
                np.int64(rng.integers(0, 6040)), np.int64(rng.integers(0, 2)),
                np.int64(rng.integers(0, 7)), np.int64(rng.integers(0, 21)),
                np.int64(rng.integers(0, 3952)), np.int64(rng.integers(0, 18)),
                np.float32(rng.integers(1, 6))))

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag: bool = True, name=None):
    """CRF Viterbi decode (reference: paddle.text.viterbi_decode /
    phi::ViterbiDecodeKernel). potentials (B, L, T), transitions (T, T).

    ``include_bos_eos_tag=True`` follows the reference convention: tag T-2 is
    BOS (its transition row scores the first step) and tag T-1 is EOS (its
    transition column scores the last step). ``lengths`` masks padded steps:
    transitions past a sequence's length neither move the score nor the tag.
    """
    import jax
    import jax.numpy as jnp

    from .core.tensor import Tensor, apply
    from .ops._helpers import ensure_tensor

    potentials = ensure_tensor(potentials)
    trans = ensure_tensor(transition_params)
    if lengths is not None:
        lengths = ensure_tensor(lengths)

    def f(emis, tr, *maybe_len):
        b, l, t = emis.shape
        lens = maybe_len[0] if maybe_len else jnp.full((b,), l, jnp.int32)

        def step(carry, inp):
            score, tag_hold = carry  # (B, T), placeholder for API symmetry
            e_t, pos = inp
            cand = score[:, :, None] + tr[None]  # (B, T, T)
            best = cand.max(axis=1) + e_t
            idx = cand.argmax(axis=1)
            active = (pos < lens)[:, None]
            new_score = jnp.where(active, best, score)
            # inactive rows point back at themselves so backtracking is a
            # no-op through padding
            self_idx = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
            idx = jnp.where(active, idx, self_idx)
            return (new_score, tag_hold), idx

        init = emis[:, 0]
        if include_bos_eos_tag:
            init = init + tr[t - 2][None]  # BOS row scores the first step
        (scores, _), backptrs = jax.lax.scan(
            step, (init, jnp.zeros((b,), jnp.int32)),
            (jnp.moveaxis(emis[:, 1:], 1, 0), jnp.arange(1, l)))
        if include_bos_eos_tag:
            scores = scores + tr[:, t - 1][None]  # EOS column scores the end
        last_tag = scores.argmax(axis=-1)  # (B,)

        def back(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        # scan emits the tag BEFORE each hop: ys = [tag_{L-1} ... tag_1],
        # final carry = tag_0
        tag0, tags_rev = jax.lax.scan(back, last_tag, backptrs[::-1])
        path = jnp.concatenate(
            [tag0[:, None], tags_rev[::-1].T], axis=1)  # (B, L)
        # zero out padded tail (reference returns only real steps)
        path = jnp.where(jnp.arange(l)[None] < lens[:, None], path, 0)
        return scores.max(axis=-1), path.astype(jnp.int64)

    args = (potentials, trans) + ((lengths,) if lengths is not None else ())
    return apply("viterbi_decode", f, *args, differentiable=False)


class ViterbiDecoder:
    """Layer-style wrapper (parity: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class _DatasetsNS:
    """``paddle.text.datasets`` namespace parity (upstream packages the
    dataset classes under text.datasets)."""

    Imdb = Imdb
    UCIHousing = UCIHousing
    Conll05st = Conll05st
    Movielens = Movielens


datasets = _DatasetsNS()
