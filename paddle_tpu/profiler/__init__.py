"""Profiler: API-parity tracing/profiling over the TPU-native stack.

Parity surface (reference: python/paddle/profiler/, C++ HostTracer with
RecordEvent RAII markers in paddle/fluid/platform/profiler/ and CUPTI-based
CudaTracer — see SURVEY.md §5). TPU-native design:

- **Host ranges** — ``RecordEvent`` markers plus an op-dispatch hook installed
  into ``paddle_tpu.core.tensor.apply`` (the single dispatch seam, the
  analogue of the reference's ad_func path that its RecordEvent markers
  instrument) feed an in-process host tracer buffer.
- **Device traces** — libtpu/XLA already emit device traces through
  ``jax.profiler``; when ``ProfilerTarget.TPU`` is requested and a trace dir
  is configured, the Profiler brackets the record window with
  ``jax.profiler.start_trace/stop_trace`` (TensorBoard/XProf consumable).
- **Export** — chrome-trace JSON of the host ranges; ``summary()`` renders
  the op-level aggregation table (reference: op summary view).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import observability as _obs

__all__ = [
    "ProfilerTarget", "ProfilerState", "Profiler", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class TracerEventType(Enum):
    Operator = 0
    UserDefined = 1
    Forward = 2
    Backward = 3
    Optimization = 4
    Dataloader = 5
    ProfileStep = 6
    Communication = 7


class _HostTracer:
    """Process-global buffer of completed host ranges.

    When the native library is available, ranges land in the C++ ring buffer
    (paddle_tpu/_native host_tracer.cc — the HostTracer equivalent); else in
    a Python list. ``drain`` normalizes both to the same event dicts.
    """

    def __init__(self) -> None:
        self._enabled = False
        self._lock = threading.Lock()
        self.events: List[Dict[str, Any]] = []
        # resolved on first enable — importing the profiler must not trigger
        # the native build
        self._native: Any = None
        self._native_resolved = False

    def _resolve_native(self) -> None:
        if not self._native_resolved:
            from .. import _native
            self._native = _native.lib if _native.available() else None
            self._native_resolved = True

    @property
    def enabled(self) -> bool:
        return self._enabled

    @enabled.setter
    def enabled(self, on: bool) -> None:
        if on == self._enabled:
            return
        if on:
            self._resolve_native()
        self._enabled = on
        if self._native is not None:
            if on:
                self._native.pt_trace_enable(1 << 16)
            else:
                self._native.pt_trace_disable()

    def emit(self, name: str, t0: float, t1: float,
             event_type: "TracerEventType") -> None:
        if not self._enabled:
            return
        if self._native is not None:
            raw = name.encode("utf-8")
            if len(raw) > 63:  # truncate on a codepoint boundary: the native
                # ring stores fixed 64-byte names and must stay valid UTF-8
                raw = raw[:63].decode("utf-8", "ignore").encode("utf-8")
            self._native.pt_trace_emit(raw, int(t0 * 1e9), int(t1 * 1e9),
                                       event_type.value,
                                       threading.get_ident() & 0xFFFFFF)
            return
        with self._lock:
            self.events.append({
                "name": name, "ts": t0, "dur": t1 - t0,
                "tid": threading.get_ident(), "type": event_type.name,
            })

    def drain(self) -> List[Dict[str, Any]]:
        if self._native is not None:
            import ctypes
            # quiesce emitters between the sizing and fill calls — a range
            # emitted in between would grow past the sized buffer and
            # truncate the JSON mid-document
            was_enabled = self._enabled
            if was_enabled:
                self._native.pt_trace_disable()
            need = self._native.pt_trace_dump(None, 0)
            buf = ctypes.create_string_buffer(int(need))
            self._native.pt_trace_dump(buf, need)
            if was_enabled:
                self._native.pt_trace_enable(1 << 16)
            raw = json.loads(buf.value.decode())
            return [{
                "name": e["name"], "ts": e["ts"] / 1e6, "dur": e["dur"] / 1e6,
                "tid": e["tid"],
                "type": TracerEventType(e["cat"]).name,
            } for e in raw]
        with self._lock:
            ev, self.events = self.events, []
        return ev


_tracer = _HostTracer()


def _op_hook(op_name: str, t0: float, t1: float) -> None:
    _tracer.emit(op_name, t0, t1, TracerEventType.Operator)


class RecordEvent:
    """RAII host-range marker (reference: platform::RecordEvent).

    Usable as a context manager or via explicit ``begin()``/``end()``::

        with profiler.RecordEvent("data_augment"):
            ...
    """

    def __init__(self, name: str,
                 event_type: TracerEventType = TracerEventType.UserDefined):
        self.name = name
        self.event_type = event_type
        self._t0: Optional[float] = None

    def begin(self) -> None:
        self._t0 = time.perf_counter()

    def end(self) -> None:
        if self._t0 is not None:
            t1 = time.perf_counter()
            _tracer.emit(self.name, self._t0, t1, self.event_type)
            if _obs.enabled():
                # profiler ranges double as metric samples: a RecordEvent
                # around e.g. "data_augment" feeds the same telemetry
                # stream whether or not a Profiler window is recording
                _obs.observe("profiler.record_event_seconds", t1 - self._t0,
                             name=self.name)
            self._t0 = None

    def __enter__(self) -> "RecordEvent":
        self.begin()
        return self

    def __exit__(self, *exc) -> None:
        self.end()


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """Step-indexed window scheduler, same contract as the reference's
    ``paddle.profiler.make_scheduler``."""
    period = closed + ready + record

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None
                          ) -> Callable[["Profiler"], None]:
    """``on_trace_ready`` factory writing chrome-trace JSON per window."""

    def handler(prof: "Profiler") -> None:
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_step{prof.step_num}.json")
        prof.export_chrome_tracing(path)

    return handler


def load_profiler_result(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


class Profiler:
    """Parity: ``paddle.profiler.Profiler``.

    ``timer_only=True`` skips tracing and only keeps step timing (the
    reference's cheap benchmark mode).
    """

    def __init__(self, *,
                 targets: Optional[Sequence[ProfilerTarget]] = None,
                 scheduler: Optional[Callable[[int], ProfilerState]] = None,
                 on_trace_ready: Optional[Callable[["Profiler"], None]] = None,
                 trace_dir: Optional[str] = None,
                 timer_only: bool = False,
                 record_shapes: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU])
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.trace_dir = trace_dir
        self.timer_only = timer_only
        self.record_shapes = record_shapes
        self.step_num = 0
        self.state = ProfilerState.CLOSED
        self._events: List[Dict[str, Any]] = []
        self._step_times: List[float] = []
        self._step_t0: Optional[float] = None
        self._device_tracing = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.state = (self.scheduler(self.step_num) if self.scheduler
                      else ProfilerState.RECORD)
        self._apply_state()
        self._step_t0 = time.perf_counter()

    def stop(self) -> None:
        if self._step_t0 is not None:
            self._step_times.append(time.perf_counter() - self._step_t0)
            self._step_t0 = None
        self._harvest()
        self._set_tracing(False)
        if self.state in (ProfilerState.RECORD,
                          ProfilerState.RECORD_AND_RETURN):
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.state = ProfilerState.CLOSED

    def step(self) -> None:
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        prev = self.state
        self.step_num += 1
        self.state = (self.scheduler(self.step_num) if self.scheduler
                      else ProfilerState.RECORD)
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._harvest()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self._apply_state()

    def __enter__(self) -> "Profiler":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ---------------------------------------------------------
    def _apply_state(self) -> None:
        recording = (not self.timer_only and self.state in
                     (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN))
        self._set_tracing(recording)

    def _set_tracing(self, on: bool) -> None:
        _tracer.enabled = on
        from ..core import tensor as _tensor_mod
        _tensor_mod._op_profile_hook = _op_hook if on else None
        wants_device = any(t in (ProfilerTarget.TPU, ProfilerTarget.GPU,
                                 ProfilerTarget.CUSTOM_DEVICE)
                           for t in self.targets)
        if wants_device and self.trace_dir:
            import jax
            if on and not self._device_tracing:
                try:
                    jax.profiler.start_trace(self.trace_dir)
                    self._device_tracing = True
                except Exception:
                    # a profile without device events is still useful, but
                    # say so: silently missing XLA traces cost a debug day
                    _obs.inc("profiler.device_trace_failures_total")
                    logging.getLogger(__name__).warning(
                        "jax.profiler.start_trace(%s) failed; profile will "
                        "carry host events only", self.trace_dir,
                        exc_info=True)
            elif not on and self._device_tracing:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    _obs.inc("profiler.device_trace_failures_total")
                    logging.getLogger(__name__).warning(
                        "jax.profiler.stop_trace() failed; device trace in "
                        "%s may be truncated", self.trace_dir, exc_info=True)
                self._device_tracing = False

    def _harvest(self) -> None:
        self._events.extend(_tracer.drain())

    # -- results -----------------------------------------------------------
    def export_chrome_tracing(self, path: str) -> None:
        trace = [{
            "name": e["name"], "ph": "X", "pid": os.getpid(),
            "tid": e["tid"], "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6,
            "cat": e["type"],
        } for e in self._events]
        with open(path, "w") as f:
            json.dump({"traceEvents": trace}, f)

    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def benchmark_summary(self) -> Dict[str, float]:
        times = self._step_times or [0.0]
        return {
            "steps": len(self._step_times),
            "avg_step_s": sum(times) / len(times),
            "min_step_s": min(times),
            "max_step_s": max(times),
        }

    def summary(self, sorted_by: str = "total", max_rows: int = 30) -> str:
        """Op-level aggregation table (reference: summary op view)."""
        agg: Dict[str, List[float]] = {}
        for e in self._events:
            agg.setdefault(e["name"], []).append(e["dur"])
        rows = [(name, len(ds), sum(ds), sum(ds) / len(ds), max(ds))
                for name, ds in agg.items()]
        key = {"total": 2, "calls": 1, "avg": 3, "max": 4}.get(sorted_by, 2)
        rows.sort(key=lambda r: r[key], reverse=True)
        out = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"
               f"{'Avg(ms)':>12}{'Max(ms)':>12}"]
        out.append("-" * 84)
        for name, calls, tot, avg, mx in rows[:max_rows]:
            out.append(f"{name[:39]:<40}{calls:>8}{tot * 1e3:>12.3f}"
                       f"{avg * 1e3:>12.3f}{mx * 1e3:>12.3f}")
        bench = self.benchmark_summary()
        out.append("-" * 84)
        out.append(f"steps: {bench['steps']}  "
                   f"avg step: {bench['avg_step_s'] * 1e3:.3f} ms")
        return "\n".join(out)
