"""Metrics: parity with ``paddle.metric`` (reference: python/paddle/metric/
metrics.py — Metric base with update/accumulate/reset/name, Accuracy,
Precision, Recall, Auc).

Metrics accumulate on host in numpy — they sit outside the jit boundary by
design (the training step returns device arrays; metric update is host-side
bookkeeping, so no XLA recompile per batch).
"""

from __future__ import annotations

import abc
from typing import List, Sequence, Union

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_numpy(x) -> np.ndarray:
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric(abc.ABC):
    """Base class: override ``update`` (per-batch, host-side), ``accumulate``
    (return the aggregated result), ``reset`` and ``name``."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Optional device-side pre-processing; default passthrough. Called
        with (pred, label) inside the step; its outputs feed ``update``."""
        return args


class Accuracy(Metric):
    """Top-k accuracy. ``topk`` may be an int or tuple of ints."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _to_numpy(pred)
        label_np = _to_numpy(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] != 1:
            label_np = np.argmax(label_np, axis=-1)  # one-hot -> index
        label_np = label_np.reshape(-1)
        idx = np.argsort(-pred_np.reshape(len(label_np), -1), axis=-1)
        top = idx[:, :self.maxk]
        return (top == label_np[:, None]).astype(np.float32)

    def update(self, correct):
        correct = _to_numpy(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = float(correct[:, :k].sum())
            self.total[i] += num
            self.count[i] += correct.shape[0]
            accs.append(num / max(correct.shape[0], 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision: TP / (TP + FP). ``pred`` is P(class=1)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_numpy(preds).reshape(-1)
        labels = _to_numpy(labels).reshape(-1)
        hard = (preds > 0.5).astype(np.int64)
        self.tp += int(np.sum((hard == 1) & (labels == 1)))
        self.fp += int(np.sum((hard == 1) & (labels == 0)))

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def reset(self):
        self.tp = 0
        self.fp = 0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall: TP / (TP + FN)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_numpy(preds).reshape(-1)
        labels = _to_numpy(labels).reshape(-1)
        hard = (preds > 0.5).astype(np.int64)
        self.tp += int(np.sum((hard == 1) & (labels == 1)))
        self.fn += int(np.sum((hard == 0) & (labels == 1)))

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def reset(self):
        self.tp = 0
        self.fn = 0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via the reference's thresholded-bucket estimator
    (num_thresholds bins over [0, 1], trapezoid rule)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_numpy(preds)
        labels = _to_numpy(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]  # P(class=1)
        preds = preds.reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._stat_pos, idx, labels == 1)
        np.add.at(self._stat_neg, idx, labels == 0)

    def accumulate(self):
        tot_pos = float(self._stat_pos.sum())
        tot_neg = float(self._stat_neg.sum())
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # sweep thresholds high→low accumulating TP/FP; trapezoid area
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(np.concatenate([[0.0], tpr]),
                                  np.concatenate([[0.0], fpr])))

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.float64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.float64)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (parity: paddle.metric.accuracy). Stays in
    jax so it can live inside a jitted eval step."""
    from ..core.tensor import apply
    from ..ops._helpers import ensure_tensor
    import jax.numpy as jnp

    input, label = ensure_tensor(input), ensure_tensor(label)

    def f(pred, lbl):
        lbl2 = lbl.reshape(-1)
        _, top_idx = __import__("jax").lax.top_k(pred, k)
        hit = jnp.any(top_idx == lbl2[:, None], axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply("accuracy", f, input, label, differentiable=False)
