"""``paddle.incubate.layers`` — legacy fused layer fns (reference:
python/paddle/incubate/layers/nn.py). The commonly-used entries map onto
the modern ops; the rest of the upstream file is PS-era sparse kernels."""

from __future__ import annotations

from ..nn import functional as F

__all__ = ["fused_embedding_seq_pool", "shuffle_batch"]


def fused_embedding_seq_pool(input, weight, pool_type="sum"):
    return F.embedding_bag(input, weight, mode=pool_type)


def shuffle_batch(x, seed=None):
    import jax
    from ..core.random import default_generator
    from ..core.tensor import Tensor

    key = default_generator.split_key() if seed is None else \
        jax.random.PRNGKey(seed)
    perm = jax.random.permutation(key, x.shape[0])
    return Tensor(x._data[perm])
