"""``paddle.incubate`` capability surface (subset that the zoos use)."""

from . import moe  # noqa: F401
from .moe import MoELayer  # noqa: F401


class distributed:  # namespace parity: paddle.incubate.distributed.models.moe
    class models:
        from . import moe
