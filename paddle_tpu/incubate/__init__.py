"""``paddle.incubate`` capability surface (subset that the zoos use)."""

from . import moe  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import optimizer  # noqa: F401
from . import xpu  # noqa: F401
from . import jit  # noqa: F401
from . import layers  # noqa: F401
from . import operators  # noqa: F401
from . import checkpoint  # noqa: F401
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401
from ..geometric import segment_sum, segment_mean, segment_max, segment_min  # noqa: F401
from .moe import MoELayer  # noqa: F401
from .nn_functional import (softmax_mask_fuse,  # noqa: F401
                            softmax_mask_fuse_upper_triangle, identity_loss)


class distributed:  # namespace parity: paddle.incubate.distributed.models.moe
    class models:
        from . import moe
