"""``paddle.incubate.operators`` (reference: python/paddle/incubate/
operators/) — graph_send_recv, softmax_mask_fuse, resnet_unit."""

from __future__ import annotations

from ..geometric import send_u_recv as graph_send_recv  # noqa: F401
from .nn_functional import softmax_mask_fuse  # noqa: F401
from .xpu import ResNetBasicBlock as resnet_unit  # noqa: F401

__all__ = ["graph_send_recv", "softmax_mask_fuse", "resnet_unit"]
