"""``paddle.incubate.multiprocessing`` — multiprocessing with tensor-aware
pickling.

Parity: python/paddle/incubate/multiprocessing/. The reference installs
CUDA-IPC / shared-memory reducers; device buffers cannot cross process
boundaries on TPU (PJRT owns them), so tensors are serialized through host
numpy — correct, if not zero-copy (documented divergence). DataLoader
workers use the same strategy.
"""

from __future__ import annotations

import copyreg
import multiprocessing
from multiprocessing import *  # noqa: F401,F403


def _rebuild_tensor(arr, stop_gradient):
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(arr), stop_gradient=stop_gradient)


def _reduce_tensor(t):
    import numpy as np
    return _rebuild_tensor, (np.asarray(t._data), t.stop_gradient)


def _install_reducers() -> None:
    from ..core.tensor import Tensor
    copyreg.pickle(Tensor, _reduce_tensor)


_install_reducers()


def get_context(method=None):
    return multiprocessing.get_context(method)
