"""``paddle.incubate.autograd`` — functional transforms (jacobian/hessian/
jvp/vjp, prim toggles).

Parity: python/paddle/incubate/autograd/. The stable entry points forward to
``paddle.autograd``'s functional API (itself jax transforms); the prim
program toggles are no-ops because jaxpr IS the primitive IR here.
"""

from __future__ import annotations

from ..autograd import hessian, jacobian  # noqa: F401
from ..autograd import jvp, vjp  # noqa: F401

__all__ = ["jacobian", "hessian", "jvp", "vjp", "enable_prim",
           "disable_prim", "prim_enabled"]

_prim = True  # everything already lowers to primitives (jaxpr)


def enable_prim() -> None:
    global _prim
    _prim = True


def disable_prim() -> None:
    global _prim
    _prim = False


def prim_enabled() -> bool:
    return _prim
