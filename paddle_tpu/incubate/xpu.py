"""``paddle.incubate.xpu`` — Kunlun-XPU-specific fused blocks.

Parity: python/paddle/incubate/xpu/resnet_block.py. The XPU fused ResNet
block is hardware-specific; on TPU the equivalent capability is the plain
layer composition (XLA fuses it), exposed under the same name so reference
scripts import cleanly.
"""

from __future__ import annotations

from .. import nn

__all__ = ["resnet_basic_block", "ResNetBasicBlock"]


class ResNetBasicBlock(nn.Layer):
    """conv-bn-relu ×2 + residual, the block the XPU kernel fuses."""

    def __init__(self, num_channels1, num_filter1, filter1_size, stride1=1,
                 num_channels2=None, num_filter2=None, filter2_size=None,
                 stride2=1, act="relu", has_shortcut=False, **kwargs):
        super().__init__()
        num_channels2 = num_channels2 or num_filter1
        num_filter2 = num_filter2 or num_filter1
        filter2_size = filter2_size or filter1_size
        pad1, pad2 = filter1_size // 2, filter2_size // 2
        self.conv1 = nn.Conv2D(num_channels1, num_filter1, filter1_size,
                               stride=stride1, padding=pad1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(num_filter1)
        self.conv2 = nn.Conv2D(num_channels2, num_filter2, filter2_size,
                               stride=stride2, padding=pad2, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(num_filter2)
        self.has_shortcut = has_shortcut
        if has_shortcut:
            self.conv3 = nn.Conv2D(num_channels1, num_filter2, 1,
                                   stride=stride1 * stride2, bias_attr=False)
            self.bn3 = nn.BatchNorm2D(num_filter2)
        self.act = getattr(nn.functional, act)

    def forward(self, x):
        h = self.act(self.bn1(self.conv1(x)))
        h = self.bn2(self.conv2(h))
        short = self.bn3(self.conv3(x)) if self.has_shortcut else x
        return self.act(h + short)


def resnet_basic_block(*args, **kwargs):
    return ResNetBasicBlock(*args, **kwargs)
